"""Pod-scale streaming data plane (mxnet_tpu/data_plane/ — ISSUE 14):
shard manifest determinism, exactly-once chunk leasing with stale-lease
fencing, cross-host work stealing, backpressure, per-host data_wait
telemetry, mid-epoch checkpoint cursors, and the wire path over a real
AsyncParamServer.

Multi-host scenarios run IN-PROCESS (N loaders sharing one ChunkLedger,
consumed on real threads) — no subprocesses, bounded polls. The
chaos-marked cells (data_host_kill / data_worker_slow) are swept per
seed by tools/chaos_matrix.sh via MXT_CHAOS_SEED.
"""
import os
import pickle
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, data_plane, recordio
from mxnet_tpu.base import MXNetError
from mxnet_tpu.data_plane import (ArrayDecoder, ChunkLedger, ImageDecoder,
                                  RemoteLedger, ShardManifest,
                                  StaleLeaseError, StreamingDataLoader)
from mxnet_tpu.membership import StaleWorkerError


def _seed():
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean_fault():
    yield
    config.set_default("MXT_FAULT", "")


def make_shards(tmp_path, n_shards=2, per_shard=40, dim=4):
    """Indexed array-record shards with GLOBALLY unique keys; record
    payload = np.full(dim, global_id) so content identifies the record."""
    shards = []
    gid = 0
    for s in range(n_shards):
        rec = str(tmp_path / ("part-%d.rec" % s))
        idx = str(tmp_path / ("part-%d.idx" % s))
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for _ in range(per_shard):
            w.write_idx(gid, recordio.pack(
                recordio.IRHeader(0, float(gid), gid, 0),
                np.full((dim,), gid, np.float32).tobytes()))
            gid += 1
        w.close()
        shards.append(rec)
    return shards


def _loader(man, ledger=None, host=0, hosts=1, seed=3, workers=1, **kw):
    return StreamingDataLoader(
        man, 4, ArrayDecoder((4,), "float32"), host_id=host,
        num_hosts=hosts, ledger=ledger, seed=seed, num_workers=workers,
        to_device=False, **kw)


def _consume_parallel(loaders):
    """Drain each loader on its own thread; returns {host: [batches]}."""
    out = {}

    def run(ldr, h):
        out[h] = list(iter(ldr))

    ts = [threading.Thread(target=run, args=(ldr, h))
          for h, ldr in loaders.items()]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
        assert not t.is_alive(), "host consumer hung"
    return out


# --------------------------------------------------------------------------
# manifest
# --------------------------------------------------------------------------
def test_manifest_deterministic_plan(tmp_path):
    shards = make_shards(tmp_path, per_shard=40)
    m1 = ShardManifest(shards, chunk_records=8)
    m2 = ShardManifest(shards, chunk_records=8)
    assert m1.manifest_id == m2.manifest_id
    assert m1.num_records == 80 and m1.num_chunks == 10
    # identical plan from identical coordinates, on any instance
    assert m1.epoch_order(2, seed=7) == m2.epoch_order(2, seed=7)
    assert m1.epoch_chunk(3, 2, seed=7) == m2.epoch_chunk(3, 2, seed=7)
    # epochs reshuffle both levels
    assert m1.epoch_order(0, seed=7) != m1.epoch_order(1, seed=7)
    assert m1.epoch_chunk(3, 0, seed=7).keys \
        != m1.epoch_chunk(3, 1, seed=7).keys
    # chunks partition the keyspace exactly, and every host table
    # covers every chunk exactly once
    owners = m1.owners(0, 3, seed=7)
    dealt = sorted(c for cids in owners.values() for c in cids)
    assert dealt == list(range(m1.num_chunks))
    keys = sorted(k for cid in range(m1.num_chunks)
                  for k in m1.epoch_chunk(cid, 0).keys)
    assert keys == sorted(k for _, k in m1.record_ids())
    # a different chunking is a DIFFERENT manifest (fencing identity)
    assert ShardManifest(shards, chunk_records=16).manifest_id \
        != m1.manifest_id


def test_recordio_reader_pickles_across_process_boundary(tmp_path):
    """Satellite: MXIndexedRecordIO seek/read_idx after __setstate__ —
    pickled-across-process readers are how process decode workers
    receive shard handles; the __getstate__ path was untested."""
    shards = make_shards(tmp_path, n_shards=1, per_shard=10)
    idx = os.path.splitext(shards[0])[0] + ".idx"
    r = recordio.MXIndexedRecordIO(idx, shards[0], "r")
    want = r.read_idx(7)
    # open reader: the clone must reopen and seek correctly
    clone = pickle.loads(pickle.dumps(r))
    assert clone.is_open
    assert clone.read_idx(7) == want
    clone.seek(3)
    assert clone.read() == r.read_idx(3)
    assert clone.keys == r.keys and clone.idx == r.idx
    clone.close()
    # closed reader: stays closed through the round-trip, reopenable
    r.close()
    closed_clone = pickle.loads(pickle.dumps(r))
    assert not closed_clone.is_open
    closed_clone.open()
    closed_clone.handle.seek(closed_clone.idx[7])
    assert closed_clone.read() == want
    closed_clone.close()


# --------------------------------------------------------------------------
# ledger
# --------------------------------------------------------------------------
def _ledger2(man, seed=1):
    led = ChunkLedger()
    led.begin_epoch(man.manifest_id, 0, man.owners(0, 2, seed=seed))
    return led


def test_ledger_lease_commit_exactly_once(tmp_path):
    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    led = _ledger2(man)
    (cid, tok), = led.lease(0, 1)
    assert led.commit(0, cid, tok) is True
    # at-least-once transport replay: same token is idempotent
    assert led.commit(0, cid, tok) is False
    # a different lease generation on a committed chunk is a zombie
    with pytest.raises(StaleLeaseError):
        led.commit(0, cid, tok + 1)
    # begin_epoch is idempotent/first-wins: joining does not reset
    assert led.begin_epoch(man.manifest_id, 0,
                           man.owners(0, 2, seed=1)) is False
    assert led.stats()["committed"] == 1
    # a DIFFERENT manifest for the same epoch is typed
    with pytest.raises(MXNetError):
        led.begin_epoch("deadbeef", 0, man.owners(0, 2, seed=1))


def test_ledger_steal_slowest_peer_and_reclaim(tmp_path):
    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    led = _ledger2(man)
    # drain host 0's queue; steals then come from host 1 (the slowest —
    # i.e. most-pending — live peer), popped from ITS tail
    own = led.lease(0, 10)
    assert len(own) == 5
    pending1 = led.stats()["pending"][1]
    stolen = led.steal(0, 1)
    assert len(stolen) == 1 and stolen[0][2] == 1
    assert led.stats()["pending"][1] == pending1 - 1
    assert led.stats()["steals"] == 1
    # fencing host 1 reclaims its pending AND leased-uncommitted chunks
    (c1, t1), = led.lease(1, 1)
    n = led.fence_host(1)
    assert n == led.stats()["reclaimable"] > 0
    re_stolen = led.steal(0, 100)
    assert {g[0] for g in re_stolen} >= {c1}
    assert all(g[2] == -1 for g in re_stolen)  # reclaim pool, not a peer
    # a fenced host can neither lease nor steal
    with pytest.raises(StaleLeaseError):
        led.lease(1, 1)
    with pytest.raises(StaleLeaseError):
        led.steal(1, 1)


def test_ledger_stale_lease_fencing_typed(tmp_path):
    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    led = _ledger2(man)
    (cid, tok), = led.lease(0, 1)
    led.fence_host(0)
    # the zombie's commit is refused even before anyone re-leases
    with pytest.raises(StaleLeaseError):
        led.commit(0, cid, tok)
    # the thief re-leases under a BUMPED generation and commits fine
    grants = {g[0]: g[1] for g in led.steal(1, 100)}
    assert grants[cid] > tok
    assert led.commit(1, cid, grants[cid]) is True
    # ... after which the zombie's replay is still typed
    with pytest.raises(StaleLeaseError):
        led.commit(0, cid, tok)
    assert led.stats()["stale_refused"] >= 2


# --------------------------------------------------------------------------
# end-to-end streaming
# --------------------------------------------------------------------------
def test_single_host_exactly_once_and_deterministic(tmp_path):
    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    runs = []
    for _ in range(2):
        batches = list(iter(_loader(man, workers=2)))
        ids = sorted(i for b in batches for i in b.ids)
        assert ids == sorted(man.record_ids())
        runs.append(batches)
    # same (manifest, seed, epoch) => bit-identical batches per chunk
    by_chunk = {}
    for b in runs[0]:
        by_chunk.setdefault(b.chunk_id, []).append(b)
    for b in runs[1]:
        ref = by_chunk[b.chunk_id].pop(0)
        assert np.array_equal(b.data, ref.data)
        assert np.array_equal(b.label, ref.label)
    # payload content matches the record id (decode correctness)
    b0 = runs[0][0]
    for j, (_, key) in enumerate(b0.ids):
        assert np.all(b0.data[j] == key)
        assert b0.label[j] == key


def test_two_host_acceptance_exactly_once_bit_identical(tmp_path):
    """ISSUE acceptance: 2 in-process hosts over a shared manifest
    consume every sample exactly once per epoch (sorted union of
    consumed record ids == dataset, no duplicates), bit-identical batch
    contents to the single-process iterator under the same epoch seed."""
    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    single = list(iter(_loader(man, workers=2)))
    led = ChunkLedger()
    out = _consume_parallel({
        0: _loader(man, ledger=led, host=0, hosts=2),
        1: _loader(man, ledger=led, host=1, hosts=2)})
    union = [i for h in out for b in out[h] for i in b.ids]
    assert sorted(union) == sorted(man.record_ids())
    assert len(union) == len(set(union))  # no duplicates
    by_chunk = {}
    for b in single:
        by_chunk.setdefault(b.chunk_id, []).append(b)
    for h in out:
        for b in out[h]:
            ref = by_chunk[b.chunk_id].pop(0)
            assert np.array_equal(b.data, ref.data)
            assert np.array_equal(b.label, ref.label)
            assert b.ids == ref.ids
    assert all(not v for v in by_chunk.values())
    # second epoch reshuffles but stays exactly-once
    b2 = list(iter(_loader(man, workers=1, start_epoch=1)))
    assert sorted(i for b in b2 for i in b.ids) == sorted(man.record_ids())
    assert [b.chunk_id for b in b2] != [b.chunk_id for b in single] or \
        any(b.ids != r.ids for b, r in zip(b2, single))


def test_backpressure_bounded_buffer_and_hbm_ledger(tmp_path):
    from mxnet_tpu import diagnostics

    man = ShardManifest(make_shards(tmp_path, per_shard=24),
                        chunk_records=8)
    ldr = _loader(man, workers=2, buffer_batches=2)
    it = iter(ldr)
    first = next(it)
    # give the workers time to run ahead as far as they ever could
    ldr.fleet._stop.wait(0.25)
    depth = ldr.fleet._q.qsize()
    assert depth <= 2, "buffer exceeded its bound (no backpressure)"
    snap = diagnostics.ledger().snapshot()
    pool = snap.get("prefetch")
    assert pool and pool["peak_bytes"] > 0, \
        "buffered batch bytes not accounted in the HBM ledger"
    assert any("data-plane" in k for k in pool["entries"]), \
        "the fleet's buffer is not a named prefetch-pool entry"
    rest = list(it)
    ids = sorted(i for b in [first] + rest for i in b.ids)
    assert ids == sorted(man.record_ids())
    # buffer bytes released at epoch end (the fleet's entry is gone)
    after = diagnostics.ledger().snapshot().get("prefetch", {})
    assert not any("data-plane-h0" in k and v
                   for k, v in after.get("entries", {}).items())
    from mxnet_tpu import telemetry

    page = telemetry.render_prometheus()
    assert 'mxt_data_queue_depth{host="0"} 0' in page


def test_data_wait_telemetry_per_host(tmp_path):
    from mxnet_tpu import telemetry

    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    led = ChunkLedger()
    _consume_parallel({0: _loader(man, ledger=led, host=0, hosts=2),
                       1: _loader(man, ledger=led, host=1, hosts=2)})
    page = telemetry.render_prometheus()
    # host-labeled gauges/counters: the fleet collector scrapes these
    # for free (registry families, no reserved labels)
    for h in ("0", "1"):
        assert 'mxt_data_records_total{host="%s"}' % h in page
        assert 'mxt_data_wait_seconds_total{host="%s"}' % h in page
        assert 'mxt_data_records_per_second{host="%s"}' % h in page
    # the data_wait phase span feeds the EXISTING histogram (goodput's
    # lost-time tap hangs off the same span)
    assert "mxt_step_phase_seconds" in page
    assert 'phase="data_wait"' in page


def test_cursor_resume_sample_exact(tmp_path):
    """A killed-and-resumed host restarts mid-epoch with no loss and no
    duplication: fully-consumed chunks are never re-decoded, a partial
    chunk's consumed head is dropped on replay (decode determinism
    makes the continuation sample-exact)."""
    man = ShardManifest(make_shards(tmp_path, n_shards=1, per_shard=64),
                        chunk_records=16)
    full = list(iter(_loader(man, seed=5)))
    l1 = _loader(man, seed=5)
    it = iter(l1)
    head = [next(it) for _ in range(6)]  # 1.5 chunks
    cur = l1.cursor()
    it.close()  # the crash point
    assert cur["epoch"] == 0 and (cur["committed"] or cur["partial"])
    l2 = _loader(man, seed=5).restore_cursor(cur)
    tail = list(iter(l2))
    ids = sorted(i for b in head + tail for i in b.ids)
    assert ids == sorted(man.record_ids())
    by_chunk = {}
    for b in full:
        by_chunk.setdefault(b.chunk_id, []).append(b)
    for b in head + tail:
        ref = by_chunk[b.chunk_id].pop(0)
        assert np.array_equal(b.data, ref.data)
    assert all(not v for v in by_chunk.values())
    # the cursor is JSON-serializable (rides CheckpointManager extra=)
    import json

    json.dumps(cur)
    # a cursor from another dataset is refused typed
    (tmp_path / "o").mkdir()
    other = ShardManifest(make_shards(tmp_path / "o", per_shard=8),
                          chunk_records=8)
    with pytest.raises(MXNetError):
        _loader(other).restore_cursor(cur)


# --------------------------------------------------------------------------
# wire path (async server transport)
# --------------------------------------------------------------------------
def test_remote_ledger_over_async_server(tmp_path):
    from mxnet_tpu.async_server import AsyncClient, AsyncParamServer

    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    srv = AsyncParamServer("127.0.0.1", 0)
    try:
        port = srv._sock.getsockname()[1]
        srv.attach_data_plane(ChunkLedger())
        ledgers = {h: RemoteLedger(AsyncClient("127.0.0.1", port,
                                               timeout=5.0))
                   for h in (0, 1)}
        out = _consume_parallel({
            h: _loader(man, ledger=ledgers[h], host=h, hosts=2)
            for h in (0, 1)})
        union = [i for h in out for b in out[h] for i in b.ids]
        assert sorted(union) == sorted(man.record_ids())
        assert len(union) == len(set(union))
        # cursor round-trips over the wire too
        cur = ledgers[0].cursor()
        assert cur["committed"] and cur["epoch"] == 0
        # zombie fencing is typed ACROSS the transport: the 'stale'
        # reply surfaces as StaleWorkerError on the zombie's side
        srv.data_plane.begin_epoch(man.manifest_id, 1,
                                   man.owners(1, 2, seed=3))
        (cid, tok), = ledgers[0].lease(0, 1)
        ledgers[0].fence_host(0)
        with pytest.raises(StaleWorkerError):
            ledgers[0].commit(0, cid, tok)
        for led in ledgers.values():
            led.close()
    finally:
        srv.close()


def test_membership_reap_fences_data_ledger(tmp_path):
    """The membership reaper's death listener reclaims a dead host's
    chunks — the wiring attach_data_plane installs."""
    from mxnet_tpu.async_server import AsyncParamServer

    man = ShardManifest(make_shards(tmp_path), chunk_records=8)
    srv = AsyncParamServer("127.0.0.1", 0)
    try:
        led = srv.attach_data_plane(ChunkLedger())
        led.begin_epoch(man.manifest_id, 0, man.owners(0, 2, seed=1))
        led.lease(1, 1)
        srv.membership.register(1, now=0.0)
        srv.membership.reap(timeout=1.0, now=100.0)  # rank 1 is dead
        stats = led.stats()
        assert 1 in stats["fenced"]
        assert stats["reclaimable"] > 0
    finally:
        srv.close()


# --------------------------------------------------------------------------
# chaos cells (swept per seed by tools/chaos_matrix.sh)
# --------------------------------------------------------------------------
@pytest.mark.chaos
def test_chaos_host_kill_steal_and_zombie_refusal(tmp_path):
    """ISSUE acceptance: host killed mid-epoch -> epoch completes with
    0 lost / 0 duplicated samples, steal counter > 0, and the stale
    zombie commit is refused typed."""
    man = ShardManifest(make_shards(tmp_path, per_shard=40),
                        chunk_records=8)
    config.set_default(
        "MXT_FAULT",
        "data_host_kill:host=1,after=2,n=1,seed=%d" % _seed())
    led = ChunkLedger()
    out = _consume_parallel({
        0: _loader(man, ledger=led, host=0, hosts=2),
        1: _loader(man, ledger=led, host=1, hosts=2)})
    stats = led.stats()
    assert stats["committed"] == stats["total"]  # epoch completed
    assert stats["steals"] > 0                   # survivors stole
    assert 1 in stats["fenced"]
    # exactly-once across the union of what BOTH consumers received
    # (the killed host dies at a chunk-commit boundary, so its consumed
    # prefix is exactly its committed chunks)
    union = [i for h in out for b in out[h] for i in b.ids]
    assert sorted(union) == sorted(man.record_ids())  # 0 lost
    assert len(union) == len(set(union))              # 0 duplicated
    # the zombie's stale lease commit is refused typed
    with pytest.raises(StaleLeaseError):
        led.commit(1, out[1][0].chunk_id if out[1] else 0, 10 ** 6)


@pytest.mark.chaos
def test_chaos_worker_slow_triggers_steal_bounded_wait(tmp_path):
    """Slow host -> the healthy peer's steal fires and the epoch
    completes exactly-once; the healthy host's data_wait stays bounded
    (it never waits on the slow peer's chunks — it steals them)."""
    import time as _time

    man = ShardManifest(make_shards(tmp_path, per_shard=40),
                        chunk_records=8)
    config.set_default(
        "MXT_FAULT",
        "data_worker_slow:host=1,ms=60,seed=%d" % _seed())
    led = ChunkLedger()
    loaders = {0: _loader(man, ledger=led, host=0, hosts=2, workers=2),
               1: _loader(man, ledger=led, host=1, hosts=2)}
    t0 = _time.perf_counter()
    out = _consume_parallel(loaders)
    dt = _time.perf_counter() - t0
    stats = led.stats()
    assert stats["committed"] == stats["total"]
    assert stats["steals"] > 0, "steal never fired against the slow host"
    union = [i for h in out for b in out[h] for i in b.ids]
    assert sorted(union) == sorted(man.record_ids())
    assert len(union) == len(set(union))
    # bounded: 10 chunks all decoded at the slow host's 60ms/chunk pace
    # would cost ~0.6s serial; stealing keeps the wall clock well under
    # the all-slow ceiling
    assert dt < 2.0


# --------------------------------------------------------------------------
# integration satellites
# --------------------------------------------------------------------------
def test_bench_streaming_input_smoke(monkeypatch):
    """The streaming_input_ab row runs end-to-end at toy size and
    reports the acceptance fields (img/s both legs, data_wait per step,
    steal count, speedup)."""
    monkeypatch.setenv("BENCH_SIAB_IMAGES", "48")
    monkeypatch.setenv("BENCH_SIAB_HW", "96")
    monkeypatch.setenv("BENCH_SIAB_RESIZE", "48")
    monkeypatch.setenv("BENCH_SIAB_CROP", "32")
    monkeypatch.setenv("BENCH_SIAB_BATCH", "8")
    monkeypatch.setenv("BENCH_SIAB_EPOCHS", "1")
    monkeypatch.setenv("BENCH_SIAB_CHUNK", "8")
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.JSONL_PATH = os.devnull  # the smoke must not pollute results
    speedup, row = bench.bench_streaming_input("cpu", "float32")
    assert row["config"] == "streaming_input_ab"
    assert row["dataloader_img_per_sec"] > 0
    assert row["data_plane_img_per_sec"] > 0
    assert row["data_plane_data_wait_ms_per_step"] > 0
    assert "steal_count" in row
    assert row["streaming_input_speedup"] == round(speedup, 4) > 0


def test_check_host_syncs_covers_data_plane():
    """Lint regression: the data-plane modules are SCANNED (a removal
    would silently drop coverage) and currently clean — worker-boundary
    numpy is sync-ok annotated, the feed path has no unmarked syncs."""
    import importlib.util

    root = os.path.join(os.path.dirname(__file__), "..")
    spec = importlib.util.spec_from_file_location(
        "check_host_syncs",
        os.path.join(root, "tools", "check_host_syncs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for rel in ("mxnet_tpu/data_plane/manifest.py",
                "mxnet_tpu/data_plane/ledger.py",
                "mxnet_tpu/data_plane/workers.py",
                "mxnet_tpu/data_plane/loader.py"):
        assert rel in mod.SCAN, "%s dropped from the sync lint" % rel
    assert mod.check(root) == []


def test_mxt_top_data_section():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    samples = {
        ("mxt_data_records_per_second", frozenset({("host", "0")})): 900.0,
        ("mxt_data_records_per_second", frozenset({("host", "1")})): 400.0,
        ("mxt_data_queue_depth", frozenset({("host", "0")})): 3,
        ("mxt_data_queue_depth", frozenset({("host", "1")})): 0,
        ("mxt_data_steals_total", frozenset({("host", "0")})): 4,
        ("mxt_data_stale_leases_total", frozenset({("host", "1")})): 1,
        ("mxt_data_wait_seconds_total", frozenset({("host", "1")})): 2.5,
    }
    frame = mod.render(samples, None, 0)
    assert "data rec/s" in frame and "h0 900" in frame
    assert "steals 4" in frame and "stale refused 1" in frame
    assert "data_wait share" in frame
    # a process without a data plane renders no data noise
    assert "data rec/s" not in mod.render({}, None, 0)
