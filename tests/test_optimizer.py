"""Optimizer tests — each update checked against a numpy reference
implementation (models tests/python/unittest/test_optimizer.py, which
compares fused optimizer ops against python reference updaters)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed

SHAPE = (7, 13)


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    w = rng.uniform(-1, 1, SHAPE).astype(np.float32)
    g = rng.uniform(-1, 1, SHAPE).astype(np.float32)
    return w, g


def _run_steps(opt, w0, grads):
    weight = nd.array(w0.copy())
    state = opt.create_state_multi_precision(0, weight)
    for g in grads:
        opt.update_multi_precision(0, weight, nd.array(g), state)
    return weight.asnumpy()


@with_seed()
def test_sgd_matches_numpy():
    w0, _ = _setup()
    rng = np.random.RandomState(1)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(4)]
    lr, wd, mom = 0.1, 0.01, 0.9

    got = _run_steps(mx.optimizer.SGD(learning_rate=lr, wd=wd, momentum=mom),
                     w0, grads)

    w = w0.copy()
    m = np.zeros_like(w)
    for g in grads:
        gg = g + wd * w
        m = mom * m - lr * gg
        w = w + m
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


@with_seed()
def test_sgd_no_momentum_and_clip():
    w0, g = _setup()
    lr, wd, clip = 0.05, 0.001, 0.3
    got = _run_steps(
        mx.optimizer.SGD(learning_rate=lr, wd=wd, clip_gradient=clip),
        w0, [g])
    gg = np.clip(g, -clip, clip) + wd * w0
    assert_almost_equal(got, w0 - lr * gg, rtol=1e-5, atol=1e-6)


@with_seed()
def test_nag_matches_numpy():
    w0, _ = _setup(3)
    rng = np.random.RandomState(4)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(3)]
    lr, wd, mom = 0.1, 0.0, 0.9
    got = _run_steps(mx.optimizer.NAG(learning_rate=lr, wd=wd, momentum=mom),
                     w0, grads)
    w = w0.copy()
    m = np.zeros_like(w)
    for g in grads:
        m = mom * m + g
        w = w - lr * (g + mom * m)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


@with_seed()
def test_adam_matches_numpy():
    w0, _ = _setup(5)
    rng = np.random.RandomState(6)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(5)]
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-8, 0.01
    got = _run_steps(
        mx.optimizer.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                          wd=wd), w0, grads)
    w = w0.copy()
    mean = np.zeros_like(w)
    var = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        gg = g + wd * w
        mean = b1 * mean + (1 - b1) * gg
        var = b2 * var + (1 - b2) * gg * gg
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * mean / (np.sqrt(var) + eps)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


@with_seed()
def test_rmsprop_matches_numpy():
    w0, _ = _setup(7)
    rng = np.random.RandomState(8)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(3)]
    lr, gamma1, eps = 1e-2, 0.9, 1e-8
    got = _run_steps(
        mx.optimizer.RMSProp(learning_rate=lr, gamma1=gamma1, epsilon=eps),
        w0, grads)
    w = w0.copy()
    n = np.zeros_like(w)
    for g in grads:
        n = (1 - gamma1) * g * g + gamma1 * n
        w = w - lr * g / np.sqrt(n + eps)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


@with_seed()
def test_rmsprop_centered_runs():
    w0, g = _setup(9)
    opt = mx.optimizer.RMSProp(learning_rate=1e-2, centered=True)
    got = _run_steps(opt, w0, [g, g])
    assert np.all(np.isfinite(got))
    assert not np.allclose(got, w0)


@with_seed()
def test_adagrad_matches_numpy():
    w0, _ = _setup(10)
    rng = np.random.RandomState(11)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(3)]
    lr, eps = 0.1, 1e-7
    got = _run_steps(mx.optimizer.AdaGrad(learning_rate=lr, eps=eps),
                     w0, grads)
    w = w0.copy()
    h = np.zeros_like(w)
    for g in grads:
        h += g * g
        w = w - lr * g / (np.sqrt(h) + eps)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


@with_seed()
def test_adadelta_matches_numpy():
    w0, _ = _setup(12)
    rng = np.random.RandomState(13)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(3)]
    rho, eps = 0.9, 1e-5
    got = _run_steps(mx.optimizer.AdaDelta(rho=rho, epsilon=eps), w0, grads)
    w = w0.copy()
    acc_g = np.zeros_like(w)
    acc_d = np.zeros_like(w)
    for g in grads:
        acc_g = rho * acc_g + (1 - rho) * g * g
        d = np.sqrt(acc_d + eps) / np.sqrt(acc_g + eps) * g
        acc_d = rho * acc_d + (1 - rho) * d * d
        w = w - d
    assert_almost_equal(got, w, rtol=1e-4, atol=1e-5)


@with_seed()
def test_ftrl_matches_numpy():
    w0, _ = _setup(14)
    rng = np.random.RandomState(15)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(3)]
    lr, lamda1, beta, wd = 0.1, 0.01, 1.0, 0.001
    got = _run_steps(
        mx.optimizer.Ftrl(learning_rate=lr, lamda1=lamda1, beta=beta, wd=wd),
        w0, grads)
    w = w0.copy()
    z = np.zeros_like(w)
    n = np.zeros_like(w)
    for g in grads:
        n_new = n + g * g
        z = z + g - (np.sqrt(n_new) - np.sqrt(n)) / lr * w
        n = n_new
        w = (np.sign(z) * lamda1 - z) / ((beta + np.sqrt(n)) / lr + wd) * \
            (np.abs(z) > lamda1)
    assert_almost_equal(got, w, rtol=1e-4, atol=1e-5)


@with_seed()
def test_signum_matches_numpy():
    w0, _ = _setup(16)
    rng = np.random.RandomState(17)
    grads = [rng.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(3)]
    lr, mom, wd_lh = 0.01, 0.9, 0.0
    got = _run_steps(
        mx.optimizer.Signum(learning_rate=lr, momentum=mom, wd_lh=wd_lh),
        w0, grads)
    w = w0.copy()
    m = np.zeros_like(w)
    for g in grads:
        m = mom * m - (1 - mom) * g
        w = (1 - lr * wd_lh) * w + lr * np.sign(m)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


@with_seed()
def test_lamb_runs_and_moves_weight():
    w0, g = _setup(18)
    got = _run_steps(mx.optimizer.LAMB(learning_rate=1e-2), w0, [g, g, g])
    assert np.all(np.isfinite(got))
    assert not np.allclose(got, w0)


def test_multi_precision_sgd_bf16():
    w0, g = _setup(19)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                           multi_precision=True)
    weight = nd.array(w0).astype("bfloat16")
    state = opt.create_state_multi_precision(0, weight)
    # master copy is fp32
    assert state[1].dtype == np.float32
    for _ in range(3):
        opt.update_multi_precision(0, weight, nd.array(g).astype("bfloat16"),
                                   state)
    # fp32 master stays close to a pure-fp32 run
    ref = _run_steps(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9),
                     w0, [g, g, g])
    assert_almost_equal(state[1].asnumpy(), ref, rtol=2e-2, atol=2e-2)


def test_create_by_name_and_registry():
    opt = mx.optimizer.create("sgd", learning_rate=0.5)
    assert isinstance(opt, mx.optimizer.SGD)
    assert opt.lr == 0.5
    assert isinstance(mx.optimizer.create("adam"), mx.optimizer.Adam)
    with pytest.raises(ValueError):
        mx.optimizer.create("definitely_not_an_optimizer")


def test_lr_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0, wd=0.1,
                           param_idx2name={0: "w", 1: "b_bias"})
    opt.set_lr_mult({"w": 0.5})
    opt.set_wd_mult({})
    assert opt._get_lr(0) == 0.5
    assert opt._get_lr(1) == 1.0
    # bias gets wd_mult 0 automatically (reference behavior)
    assert opt._get_wd(1) == 0.0


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler

    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25
    m = MultiFactorScheduler(step=[5, 15], factor=0.1, base_lr=1.0)
    assert m(1) == 1.0
    assert abs(m(6) - 0.1) < 1e-12
    assert abs(m(16) - 0.01) < 1e-12


def test_lr_scheduler_warmup_poly_cosine():
    from mxnet_tpu.lr_scheduler import PolyScheduler, CosineScheduler

    p = PolyScheduler(max_update=100, base_lr=1.0, pwr=2, warmup_steps=10,
                      warmup_begin_lr=0.0)
    assert p(5) == 0.5  # linear warmup
    assert abs(p(100)) < 1e-6
    c = CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.1)
    assert abs(c(0) - 1.0) < 1e-12
    assert abs(c(100) - 0.1) < 1e-12


def test_updater_state_roundtrip():
    w0, g = _setup(20)
    opt = mx.optimizer.Adam(learning_rate=1e-2)
    updater = mx.optimizer.get_updater(opt)
    weight = nd.array(w0.copy())
    updater(0, nd.array(g), weight)
    blob = updater.get_states(dump_optimizer=True)

    opt2 = mx.optimizer.Adam(learning_rate=1e-2)
    updater2 = mx.optimizer.get_updater(opt2)
    updater2.set_states(blob)
    w1 = nd.array(weight.asnumpy())
    w2 = nd.array(weight.asnumpy())
    updater(0, nd.array(g), w1)
    updater2(0, nd.array(g), w2)
    assert_almost_equal(w1.asnumpy(), w2.asnumpy(), rtol=1e-6, atol=1e-7)
