"""NDArray basics (modeled on tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed()
def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert (a.asnumpy() == 0).all()
    b = nd.ones((2,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.0)
    assert (c.asnumpy() == 7).all()
    d = nd.arange(0, 10, 2)
    assert_almost_equal(d, np.arange(0, 10, 2, dtype=np.float32))
    e = nd.array([[1, 2], [3, 4]])
    assert e.shape == (2, 2)


@with_seed()
def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]]))
    assert_almost_equal(a - b, np.array([[-4, -4], [-4, -4]]))
    assert_almost_equal(a * b, np.array([[5, 12], [21, 32]]))
    assert_almost_equal(b / a, np.array([[5, 3], [7 / 3, 2]]))
    assert_almost_equal(a + 1, np.array([[2, 3], [4, 5]]))
    assert_almost_equal(1 - a, np.array([[0, -1], [-2, -3]]))
    assert_almost_equal(2 * a, np.array([[2, 4], [6, 8]]))
    assert_almost_equal(a ** 2, np.array([[1, 4], [9, 16]]))
    assert_almost_equal(-a, -a.asnumpy())
    assert_almost_equal(abs(-a), a.asnumpy())


@with_seed()
def test_broadcast_binary():
    a = nd.array(np.random.rand(3, 1))
    b = nd.array(np.random.rand(1, 4))
    assert (a + b).shape == (3, 4)
    assert_almost_equal(nd.broadcast_add(a, b), a.asnumpy() + b.asnumpy())
    assert_almost_equal(nd.broadcast_maximum(a, b),
                        np.maximum(a.asnumpy(), b.asnumpy()))


@with_seed()
def test_comparison_dtype():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    eq = (a == b)
    assert eq.dtype == np.float32  # reference returns input dtype, not bool
    assert_almost_equal(eq, np.array([0.0, 1.0, 0.0]))
    assert_almost_equal(a < b, np.array([1.0, 0.0, 0.0]))


@with_seed()
def test_mutation_and_views():
    a = nd.zeros((4, 4))
    a[1] = 1.0
    assert_almost_equal(a.asnumpy()[1], np.ones(4))
    a[2, 3] = 5.0
    assert a.asnumpy()[2, 3] == 5.0
    a[:, 0] = nd.array([9.0, 9.0, 9.0, 9.0])
    assert (a.asnumpy()[:, 0] == 9).all()
    # view read/write coherence (reference: slices share the Chunk)
    v = a[1:3]
    assert v.shape == (2, 4)
    a[1] = 7.0
    assert (v.asnumpy()[0] == 7).all()  # view sees base mutation
    v[0] = 3.0
    assert (a.asnumpy()[1] == 3).all()  # base sees view mutation


@with_seed()
def test_inplace_ops():
    a = nd.ones((2, 2))
    orig = a
    a += 1
    assert (a.asnumpy() == 2).all()
    assert orig is a
    a *= 3
    assert (a.asnumpy() == 6).all()
    a /= 2
    assert (a.asnumpy() == 3).all()


@with_seed()
def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    b = nd.zeros((2, 8))
    assert b.reshape((0, -4, -1, 2)).shape == (2, 4, 2)
    assert b.reshape((0, -4, 2, 4)).shape == (2, 2, 4)


@with_seed()
def test_reduce():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(nd.sum(a), x.sum())
    assert_almost_equal(nd.sum(a, axis=1), x.sum(axis=1))
    assert_almost_equal(nd.sum(a, axis=(0, 2), keepdims=True),
                        x.sum(axis=(0, 2), keepdims=True))
    assert_almost_equal(nd.sum(a, axis=1, exclude=True), x.sum(axis=(0, 2)))
    assert_almost_equal(nd.mean(a, axis=0), x.mean(axis=0))
    assert_almost_equal(nd.max(a, axis=2), x.max(axis=2))
    assert_almost_equal(a.sum(axis=1), x.sum(axis=1))  # method route


@with_seed()
def test_dot():
    x = np.random.rand(4, 5).astype(np.float32)
    y = np.random.rand(5, 6).astype(np.float32)
    assert_almost_equal(nd.dot(nd.array(x), nd.array(y)), x @ y, rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(x), nd.array(y.T), transpose_b=True), x @ y, rtol=1e-4
    )
    bx = np.random.rand(3, 4, 5).astype(np.float32)
    by = np.random.rand(3, 5, 2).astype(np.float32)
    assert_almost_equal(nd.batch_dot(nd.array(bx), nd.array(by)), bx @ by,
                        rtol=1e-4)


@with_seed()
def test_slicing_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    a = nd.array(x)
    assert_almost_equal(nd.slice(a, begin=(0, 1), end=(2, 3)), x[0:2, 1:3])
    assert_almost_equal(nd.slice_axis(a, axis=2, begin=1, end=3), x[:, :, 1:3])
    assert_almost_equal(nd.concat(a, a, dim=1), np.concatenate([x, x], 1))
    parts = nd.split(a, num_outputs=3, axis=1)
    assert len(parts) == 3
    assert_almost_equal(parts[1], x[:, 1:2, :])
    assert_almost_equal(nd.flip(a, axis=2), x[:, :, ::-1])
    assert_almost_equal(nd.tile(a, reps=(1, 2, 1)), np.tile(x, (1, 2, 1)))
    assert_almost_equal(nd.transpose(a, axes=(2, 0, 1)), x.transpose(2, 0, 1))
    assert_almost_equal(nd.expand_dims(a, axis=1), x[:, None])
    assert_almost_equal(a.flatten(), x.reshape(2, -1))


@with_seed()
def test_take_and_indexing_ops():
    x = np.random.rand(5, 3).astype(np.float32)
    a = nd.array(x)
    idx = nd.array([0, 4, 2], dtype="int32")
    assert_almost_equal(nd.take(a, idx), x[[0, 4, 2]])
    # clip mode
    idx2 = nd.array([-1, 10], dtype="int32")
    assert_almost_equal(nd.take(a, idx2), x[[0, 4]])
    oh = nd.one_hot(nd.array([0, 2], dtype="int32"), depth=3)
    assert_almost_equal(oh, np.eye(3, dtype=np.float32)[[0, 2]])
    p = nd.pick(a, nd.array([0, 1, 2, 0, 1]), axis=1)
    assert_almost_equal(p, x[np.arange(5), [0, 1, 2, 0, 1]])


@with_seed()
def test_ordering():
    x = np.random.rand(4, 6).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(nd.sort(a, axis=1), np.sort(x, axis=1))
    assert_almost_equal(nd.sort(a, axis=1, is_ascend=False),
                        -np.sort(-x, axis=1))
    tk = nd.topk(a, axis=1, k=2, ret_typ="value")
    assert_almost_equal(tk, -np.sort(-x, axis=1)[:, :2])


@with_seed()
def test_astype_and_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = nd.cast(a, dtype="float64")
    assert c.dtype == np.float64
    d = a.astype("bfloat16")
    assert d.dtype.name.startswith("bfloat16") or d.dtype.itemsize == 2


@with_seed()
def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    a = nd.array([1.0, 2.0])
    b = nd.array([[3.0]])
    nd.save(fname, [a, b])
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2
    assert_almost_equal(loaded[0], a.asnumpy())
    nd.save(fname, {"x": a, "y": b})
    loaded = nd.load(fname)
    assert set(loaded.keys()) == {"x", "y"}
    assert_almost_equal(loaded["y"], b.asnumpy())


@with_seed()
def test_random_basic():
    mx.random.seed(42)
    a = nd.random.uniform(0, 1, shape=(100,))
    assert a.shape == (100,)
    assert 0 <= a.asnumpy().min() and a.asnumpy().max() <= 1
    mx.random.seed(42)
    b = nd.random.uniform(0, 1, shape=(100,))
    assert_almost_equal(a, b)  # seeding reproduces
    n = nd.random.normal(0, 1, shape=(2000,))
    assert abs(float(n.asnumpy().mean())) < 0.15
    r = nd.random.randint(0, 10, shape=(50,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10


@with_seed()
def test_scalar_conversion():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == np.float32(3.5)
    with pytest.raises(ValueError):
        nd.zeros((2,)).asscalar()


@with_seed()
def test_context_and_copy():
    a = nd.ones((2, 2), ctx=mx.cpu())
    assert a.context.device_type == "cpu"
    b = a.copyto(mx.cpu(0))
    b[0, 0] = 5.0
    assert a.asnumpy()[0, 0] == 1.0  # copy, not alias
    c = a.as_in_context(mx.cpu(0))
    assert c is a  # same ctx returns self (reference behavior)


@with_seed()
def test_wait_and_waitall():
    a = nd.ones((8, 8))
    b = a * 2
    b.wait_to_read()
    nd.waitall()
    assert (b.asnumpy() == 2).all()
