"""AttrScope / NameManager / engine shims / FeedForward
(ref: tests/python/unittest/{test_attr.py,test_symbol.py,
test_model*.py})."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import with_seed


def test_attr_scope_basic():
    with mx.AttrScope(group="4", data="great"):
        x = mx.sym.var("data", attr={"dtype": "data", "group": "1"})
        y = mx.sym.var("lhs")
    assert x.attr("group") == "1"      # explicit wins
    assert x.attr("dtype") == "data"
    assert y.attr("group") == "4"
    assert y.attr("data") == "great"
    z = mx.sym.var("after")
    assert z.attr("group") is None     # scope exited


def test_attr_scope_nesting_and_ops():
    with mx.AttrScope(ctx_group="stage1"):
        a = mx.sym.var("a")
        with mx.AttrScope(ctx_group="stage2", lr_mult="0.5"):
            fc = mx.sym.FullyConnected(a, num_hidden=3, name="fc_in")
        b = mx.sym.FullyConnected(a, num_hidden=3, name="fc_out")
    assert fc.attr("ctx_group") == "stage2"
    assert fc.attr("lr_mult") == "0.5"
    assert b.attr("ctx_group") == "stage1"
    assert b.attr("lr_mult") is None


def test_attr_scope_rejects_non_string():
    with pytest.raises(ValueError):
        mx.AttrScope(group=4)


def test_name_prefix():
    with mx.name.Prefix("mynet_"):
        a = mx.sym.var("x")
        fc = mx.sym.FullyConnected(a, num_hidden=2)
    assert fc.name.startswith("mynet_fullyconnected")
    fc2 = mx.sym.FullyConnected(a, num_hidden=2)
    assert not fc2.name.startswith("mynet_")


def test_name_manager_counts():
    with mx.name.NameManager():
        a = mx.sym.var("x")
        f1 = mx.sym.FullyConnected(a, num_hidden=2)
        f2 = mx.sym.FullyConnected(a, num_hidden=2)
    # fresh manager numbers from 0 within its scope
    base = f1.name.rstrip("0123456789")
    assert f1.name == base + "0" and f2.name == base + "1"


def test_engine_bulk_shim():
    prev = mx.engine.set_bulk_size(8)
    assert mx.engine.set_bulk_size(prev) == 8
    with mx.engine.bulk(32):
        x = mx.nd.ones((2, 2)) + 1
    assert float(x.sum().asscalar()) == 8.0


@with_seed()
def test_feedforward_fit_predict_save_load(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (120, 4)).astype(np.float32)
    w = np.array([[1.0, -1.5, 2.0, 0.3]], dtype=np.float32)
    y = x @ w.T
    data = mx.sym.var("data")
    label = mx.sym.var("lin_label")
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(data, num_hidden=1, name="fc"),
        label, name="lro")

    model = mx.model.FeedForward(net, num_epoch=40, optimizer="sgd",
                                 numpy_batch_size=12, learning_rate=0.1)
    model.fit(x, y, eval_metric="mse")
    pred = model.predict(x)
    np.testing.assert_allclose(pred, y, atol=0.05)

    prefix = str(tmp_path / "ff")
    model.save(prefix, 1)
    loaded = mx.model.FeedForward.load(prefix, 1)
    pred2 = loaded.predict(x)
    np.testing.assert_allclose(pred2, pred, rtol=1e-5, atol=1e-6)

    mse = loaded.score(
        mx.io.NDArrayIter(x, y, batch_size=12, label_name="lin_label"),
        eval_metric="mse")
    assert mse < 0.01


@with_seed()
def test_feedforward_predict_trims_pad():
    """100 samples / batch 12: predict must return exactly 100 rows (the
    wrapped pad batch is trimmed, ref: model.py real_size)."""
    rng = np.random.RandomState(3)
    x = rng.uniform(-1, 1, (100, 4)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=1,
                              name="fc"),
        mx.sym.var("lin_label"), name="lro")
    model = mx.model.FeedForward(net, num_epoch=2, optimizer="sgd",
                                 numpy_batch_size=12, learning_rate=0.01)
    model.fit(x, y, eval_metric="mse")
    pred = model.predict(x)
    assert pred.shape[0] == 100, pred.shape
    # unfitted model must raise loudly, not crash opaquely
    fresh = mx.model.FeedForward(net, numpy_batch_size=12)
    with pytest.raises(Exception, match="no parameters"):
        fresh.predict(x)


@with_seed()
def test_feedforward_create():
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (60, 3)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32).ravel()
    data = mx.sym.var("data")
    sm = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2, name="fc"),
        mx.sym.var("softmax_label"), name="softmax")
    model = mx.model.FeedForward.create(
        sm, x, y, num_epoch=20, optimizer="sgd", numpy_batch_size=10,
        learning_rate=0.5)
    acc = model.score(mx.io.NDArrayIter(x, y, batch_size=10))
    assert acc > 0.8, acc
