"""AttrScope / NameManager / engine shims / FeedForward
(ref: tests/python/unittest/{test_attr.py,test_symbol.py,
test_model*.py})."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import with_seed


def test_attr_scope_basic():
    with mx.AttrScope(group="4", data="great"):
        x = mx.sym.var("data", attr={"dtype": "data", "group": "1"})
        y = mx.sym.var("lhs")
    assert x.attr("group") == "1"      # explicit wins
    assert x.attr("dtype") == "data"
    assert y.attr("group") == "4"
    assert y.attr("data") == "great"
    z = mx.sym.var("after")
    assert z.attr("group") is None     # scope exited


def test_attr_scope_nesting_and_ops():
    with mx.AttrScope(ctx_group="stage1"):
        a = mx.sym.var("a")
        with mx.AttrScope(ctx_group="stage2", lr_mult="0.5"):
            fc = mx.sym.FullyConnected(a, num_hidden=3, name="fc_in")
        b = mx.sym.FullyConnected(a, num_hidden=3, name="fc_out")
    assert fc.attr("ctx_group") == "stage2"
    assert fc.attr("lr_mult") == "0.5"
    assert b.attr("ctx_group") == "stage1"
    assert b.attr("lr_mult") is None


def test_attr_scope_never_leaks_into_op_params():
    """An annotation named like an op parameter (Dropout's 'p') must not
    change execution."""
    d = mx.sym.var("data")
    with mx.AttrScope(p="stage1", mode="whatever"):
        out = mx.sym.Dropout(d, p=0.0)
    assert out.attr("p") == "stage1"  # annotation visible as attr
    x = mx.nd.ones((2, 3))
    res = out.bind(mx.cpu(), {"data": x}).forward()[0]
    np.testing.assert_array_equal(res.asnumpy(), x.asnumpy())


def test_annotations_roundtrip_json():
    with mx.AttrScope(ctx_group="dev1", lr_mult="0.5"):
        fc = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3,
                                   name="fc")
    # auto-created weight/bias variables inherit the scope attrs
    assert fc.attr_dict["fc_weight"]["ctx_group"] == "dev1"
    s2 = mx.sym.load_json(fc.tojson())
    assert s2.attr("ctx_group") == "dev1"
    assert s2.attr("lr_mult") == "0.5"
    assert s2.attr("num_hidden") == "3"  # params still visible as attrs
    # and the loaded graph still executes with the right params
    out = s2.bind(mx.cpu(), {
        "data": mx.nd.ones((2, 4)),
        "fc_weight": mx.nd.ones((3, 4)),
        "fc_bias": mx.nd.zeros((3,))}).forward()[0]
    assert out.shape == (2, 3)


def test_colliding_annotation_roundtrips_without_clobber():
    """An annotation named like a param (Dropout's 'p') must survive
    save/load without corrupting the execution value."""
    with mx.AttrScope(p="stage1"):
        out = mx.sym.Dropout(mx.sym.var("data"), p=0.25)
    s2 = mx.sym.load_json(out.tojson())
    assert s2.attr("p") == "stage1"       # annotation preserved
    x = mx.nd.ones((2, 3))
    res = s2.bind(mx.cpu(), {"data": x}).forward()[0]
    np.testing.assert_array_equal(res.asnumpy(), x.asnumpy())  # p=0.25,
    # inference mode -> identity; a str p would TypeError here


def test_unpassed_param_annotation_roundtrips_inert():
    """An annotation matching an UNPASSED op param ('mode') must not
    become the execution value after save/load."""
    with mx.AttrScope(mode="always"):
        out = mx.sym.Dropout(mx.sym.var("data"), p=0.5)
    s2 = mx.sym.load_json(out.tojson())
    assert s2.attr("mode") == "always"    # annotation preserved
    x = mx.nd.ones((2, 100))
    res = s2.bind(mx.cpu(), {"data": x}).forward()[0]
    # inference: identity. If 'mode' leaked as the execution param,
    # mode='always' would drop half the elements here.
    np.testing.assert_array_equal(res.asnumpy(), x.asnumpy())


def test_attr_scope_rejects_non_string():
    with pytest.raises(ValueError):
        mx.AttrScope(group=4)


def test_name_prefix():
    with mx.name.Prefix("mynet_"):
        a = mx.sym.var("x")
        fc = mx.sym.FullyConnected(a, num_hidden=2)
    assert fc.name.startswith("mynet_fullyconnected")
    fc2 = mx.sym.FullyConnected(a, num_hidden=2)
    assert not fc2.name.startswith("mynet_")


def test_name_manager_counts():
    with mx.name.NameManager():
        a = mx.sym.var("x")
        f1 = mx.sym.FullyConnected(a, num_hidden=2)
        f2 = mx.sym.FullyConnected(a, num_hidden=2)
    # fresh manager numbers from 0 within its scope
    base = f1.name.rstrip("0123456789")
    assert f1.name == base + "0" and f2.name == base + "1"


def test_engine_bulk_shim():
    prev = mx.engine.set_bulk_size(8)
    assert mx.engine.set_bulk_size(prev) == 8
    with mx.engine.bulk(32):
        x = mx.nd.ones((2, 2)) + 1
    assert float(x.sum().asscalar()) == 8.0


@with_seed()
def test_feedforward_fit_predict_save_load(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (120, 4)).astype(np.float32)
    w = np.array([[1.0, -1.5, 2.0, 0.3]], dtype=np.float32)
    y = x @ w.T
    data = mx.sym.var("data")
    label = mx.sym.var("lin_label")
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(data, num_hidden=1, name="fc"),
        label, name="lro")

    model = mx.model.FeedForward(net, num_epoch=40, optimizer="sgd",
                                 numpy_batch_size=12, learning_rate=0.1)
    model.fit(x, y, eval_metric="mse")
    pred = model.predict(x)
    np.testing.assert_allclose(pred, y, atol=0.05)

    prefix = str(tmp_path / "ff")
    model.save(prefix, 1)
    loaded = mx.model.FeedForward.load(prefix, 1)
    pred2 = loaded.predict(x)
    np.testing.assert_allclose(pred2, pred, rtol=1e-5, atol=1e-6)

    mse = loaded.score(
        mx.io.NDArrayIter(x, y, batch_size=12, label_name="lin_label"),
        eval_metric="mse")
    assert mse < 0.01


@with_seed()
def test_feedforward_predict_trims_pad():
    """100 samples / batch 12: predict must return exactly 100 rows (the
    wrapped pad batch is trimmed, ref: model.py real_size)."""
    rng = np.random.RandomState(3)
    x = rng.uniform(-1, 1, (100, 4)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=1,
                              name="fc"),
        mx.sym.var("lin_label"), name="lro")
    model = mx.model.FeedForward(net, num_epoch=2, optimizer="sgd",
                                 numpy_batch_size=12, learning_rate=0.01)
    model.fit(x, y, eval_metric="mse")
    pred = model.predict(x)
    assert pred.shape[0] == 100, pred.shape
    # unfitted model must raise loudly, not crash opaquely
    fresh = mx.model.FeedForward(net, numpy_batch_size=12)
    with pytest.raises(Exception, match="no parameters"):
        fresh.predict(x)
    # empty prediction window raises a clear error
    with pytest.raises(Exception, match="no batches"):
        model.predict(x, num_batch=0)


@with_seed()
def test_feedforward_custom_input_name():
    """Input names come from the iterator, not hard-coded 'data'."""
    rng = np.random.RandomState(5)
    x = rng.uniform(-1, 1, (40, 3)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(mx.sym.var("img"), num_hidden=1,
                              name="fc"),
        mx.sym.var("lin_label"), name="lro")
    it = mx.io.NDArrayIter(
        {"img": x}, {"lin_label": y}, batch_size=8)
    model = mx.model.FeedForward(net, num_epoch=30, optimizer="sgd",
                                 learning_rate=0.1)
    model.fit(it, eval_metric="mse")
    pred = model.predict(mx.io.NDArrayIter({"img": x}, batch_size=8))
    np.testing.assert_allclose(pred, y, atol=0.05)


def test_contrib_namespaces():
    """mx.nd.contrib.X / mx.sym.contrib.X resolve the _contrib_-prefixed
    registry ops (ref: register.py prefix-module convention)."""
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(2, 8).astype(np.float32))
    re_im = mx.nd.contrib.fft(x)
    assert re_im.shape[-1] == 16  # interleaved complex like the ref op
    # symbolic form composes too
    s = mx.sym.contrib.fft(mx.sym.var("data"))
    out = s.bind(mx.cpu(), {"data": x}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), re_im.asnumpy(), rtol=1e-5)
    assert "fft" in dir(mx.nd.contrib)
    with pytest.raises(AttributeError):
        mx.nd.contrib.no_such_op


@with_seed()
def test_module_save_load_params_iter_predict(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (40, 3)).astype(np.float32)
    y = (x @ np.array([[1.0, -1.0, 2.0]], dtype=np.float32).T)
    it = mx.io.NDArrayIter(x, y, batch_size=8, label_name="lin_label")
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=1,
                              name="fc"),
        mx.sym.var("lin_label"), name="lro")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("lin_label",))
    mod.fit(it, num_epoch=20, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.1),),
            eval_metric="mse")
    f = str(tmp_path / "m.params")
    mod.save_params(f)

    mod2 = mx.mod.Module(net, data_names=("data",),
                         label_names=("lin_label",))
    mod2.bind(data_shapes=it.provide_data,
              label_shapes=it.provide_label, for_training=False)
    mod2.init_params()
    mod2.load_params(f)
    np.testing.assert_allclose(
        mod2.get_params()[0]["fc_weight"].asnumpy(),
        mod.get_params()[0]["fc_weight"].asnumpy(), rtol=1e-6)

    # iter_predict walks batches with indices
    seen = 0
    for outputs, i, batch in mod2.iter_predict(it):
        assert i == seen
        assert outputs[0].shape[0] == 8
        seen += 1
    assert seen == 5


@with_seed()
def test_feedforward_create():
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (60, 3)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32).ravel()
    data = mx.sym.var("data")
    sm = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2, name="fc"),
        mx.sym.var("softmax_label"), name="softmax")
    model = mx.model.FeedForward.create(
        sm, x, y, num_epoch=20, optimizer="sgd", numpy_batch_size=10,
        learning_rate=0.5)
    acc = model.score(mx.io.NDArrayIter(x, y, batch_size=10))
    assert acc > 0.8, acc
