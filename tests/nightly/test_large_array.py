"""Large-tensor tier (ref: tests/nightly/test_large_array.py — the
reference's >2^31-element lane guarding against int32 index overflow in
kernels). Run with ``MXT_TEST_NIGHTLY=1`` on a host with ≥16 GB free.

XLA's index arithmetic is 64-bit-safe, but OUR framework code (shape
math, flattening, recordio offsets, reductions) must be too — these pin
the paths a 32-bit assumption would break. Arrays are int8/bool where
possible to keep the footprint ~2-5 GB per test."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

pytestmark = pytest.mark.nightly

LARGE = 2 ** 31 + 7  # one past the int32 boundary
_mem_kb = 0
try:
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemAvailable"):
                _mem_kb = int(line.split()[1])
except OSError:
    pass
needs_ram = pytest.mark.skipif(
    _mem_kb < 16 * 1024 * 1024,
    reason="needs >=16 GB available RAM for >2^31-element arrays")


@needs_ram
def test_create_and_reduce_past_int32_elements():
    x = nd.ones((LARGE,), dtype="int8")
    assert x.size == LARGE
    # int8 accumulation would wrap; widen via a CHUNKED reduction — a
    # whole-array astype('int64') would materialize ~17 GB and blow past
    # the RAM gate (the traversal past 2^31 still exercises 64-bit
    # offsets on the final chunk)
    q = LARGE // 4
    bounds = [0, q, 2 * q, 3 * q, LARGE]
    total = sum(
        int(x[a:b].astype("int64").sum().asscalar())
        for a, b in zip(bounds, bounds[1:]))
    assert total == LARGE


@needs_ram
def test_indexing_past_int32_boundary():
    x = nd.zeros((LARGE,), dtype="int8")
    x[LARGE - 1] = 7
    x[2 ** 31 + 1] = 3
    assert int(x[LARGE - 1].asscalar()) == 7
    assert int(x[2 ** 31 + 1].asscalar()) == 3
    assert int(x[0].asscalar()) == 0


@needs_ram
def test_reshape_and_slice_2d_large():
    rows = 2 ** 16 + 1
    cols = 2 ** 15 + 1  # rows*cols > 2^31
    x = nd.ones((rows, cols), dtype="int8")
    flat = x.reshape((-1,))
    assert flat.shape == (rows * cols,)
    tail = x[rows - 1, cols - 3:]
    np.testing.assert_array_equal(tail.asnumpy(), np.ones(3, np.int8))


@needs_ram
def test_argmax_lands_past_int32():
    x = nd.zeros((LARGE,), dtype="int8")
    x[2 ** 31 + 3] = 1
    # default f32 indices are exact only to 2^24 (reference parity) —
    # the large-tensor escape hatch is dtype='int64'
    idx = int(nd.argmax(x, axis=0, dtype="int64").asscalar())
    assert idx == 2 ** 31 + 3


@needs_ram
def test_take_with_int64_indices():
    # gather FROM a large array with indices beyond 2^31
    big = nd.ones((LARGE,), dtype="int8")
    got = nd.take(big, nd.array(np.array([0, 2 ** 31 + 5, LARGE - 1],
                                         np.int64)))
    np.testing.assert_array_equal(got.asnumpy(), np.ones(3, np.int8))


def test_shape_size_arithmetic_is_64bit():
    """Pure shape math (no allocation): size/infer paths must not wrap."""
    from mxnet_tpu import symbol as sym
    s = sym.Variable("data", shape=(2 ** 20, 2 ** 12))
    out = sym.Reshape(s, shape=(-1,))
    _, out_shapes, _ = out.infer_shape(data=(2 ** 20, 2 ** 12))
    assert out_shapes[0] == (2 ** 32,)
