"""Model backwards compatibility (ref: tests/nightly/
model_backwards_compatibility_check/ — checkpoints written by OLDER
builds must keep loading and producing identical outputs).

Golden fixtures live in tests/fixtures/backcompat_r5/ (committed, never
regenerated): a round-5 binary checkpoint pair, a pre-r5 npz-era params
file, and the pinned input/output. Cheap enough to run in the default
suite — intentionally NOT nightly-gated, so a format regression fails CI
immediately."""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd

FIX = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "fixtures", "backcompat_r5")
PFX = os.path.join(FIX, "mlp")


def _pinned_io():
    z = np.load(os.path.join(FIX, "io.npz"))
    return z["x"], z["y"]


def test_r5_binary_checkpoint_loads_and_matches():
    X, want = _pinned_io()
    symbol, arg, aux = mx.model.load_checkpoint(PFX, 0)
    mod = mx.module.Module(symbol, data_names=["data"], label_names=None)
    mod.bind(data_shapes=[("data", X.shape)], for_training=False)
    mod.set_params(arg, aux)
    mod.forward(mx.io.DataBatch(data=[nd.array(X)], label=None),
                is_train=False)
    got = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_r5_checkpoint_loads_through_symbolblock():
    X, want = _pinned_io()
    from mxnet_tpu.gluon import SymbolBlock
    # the graph ends in SoftmaxOutput, so the label is an input of the
    # imported block (reference convention: list it in input_names and
    # feed a dummy at inference — SoftmaxOutput ignores it)
    blk = SymbolBlock.imports(PFX + "-symbol.json",
                              ["data", "softmax_label"],
                              PFX + "-0000.params")
    got = blk(nd.array(X), nd.zeros((X.shape[0],))).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pre_r5_npz_era_params_still_load():
    """Params written by rounds 1-4 (npz byte format) keep loading."""
    X, want = _pinned_io()
    loaded = nd.load(os.path.join(FIX, "mlp-npz-era.params"))
    from mxnet_tpu.model import unpack_param_dict
    arg, aux = unpack_param_dict(loaded)
    symbol = mx.symbol.load(PFX + "-symbol.json")
    mod = mx.module.Module(symbol, data_names=["data"], label_names=None)
    mod.bind(data_shapes=[("data", X.shape)], for_training=False)
    mod.set_params(arg, aux)
    mod.forward(mx.io.DataBatch(data=[nd.array(X)], label=None),
                is_train=False)
    got = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
