"""Shared-prefix KV reuse + prefill/decode disaggregation (PR 16:
mxnet_tpu/serving/prefix.py, kv_cache refcounts/copy-on-write, the
fleet's srv_ship_pages/srv_adopt_pages handoff, and the router's
role-aware dispatch).

Covers: the blake2b chain hash, per-page refcount invariants (a shared
page is never freed while referenced; the last reference returns it to
the free list), copy-on-write on the quantized pool carrying scale
planes, token-exact reuse vs the cache-free oracle (full-match COW
path included), prefix-discounted admission with LRU index shedding
under pressure, the unchanged <=1-sync-per-K decode protocol,
disaggregated handoff token-exactness A->B with the
prefill->ship->adopt->decode trace chain, idempotent re-ship, the
seeded prefill-kill chaos cell swept by tools/chaos_matrix.sh, the
mxt_top prefix line, and the host-sync lint inclusion.
"""
import os
import time

import numpy as np
import pytest

from mxnet_tpu import engine as eng_mod
from mxnet_tpu import profiler, serving, telemetry, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (ContinuousBatcher, DecodeEngine,
                               FleetRouter, PagedKVCache, PrefixIndex,
                               Request, TinyDecoder)
from mxnet_tpu.telemetry_fleet import chrome_trace, trace_tree


def _seed():
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch, tmp_path):
    """Dead replicas surface in milliseconds; every test gets its own
    tuning table and a clean trace-span log."""
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.02")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.05")
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    telemetry.clear_trace_spans()
    yield
    telemetry.clear_trace_spans()
    tuning.reset()


MODEL = TinyDecoder(vocab=64, num_layers=1, num_heads=2, head_dim=8,
                    max_len=256)
PARAMS = MODEL.init_params(3)
BASE = list(range(1, 17))   # page-aligned 2-page prompt (page_size 8)


def _engine(pages=64, slots=2, quantized=False, prefix=True,
            max_context=64):
    return DecodeEngine(
        MODEL, params=PARAMS, slots=slots,
        cache=PagedKVCache(1, 2, 8, num_pages=pages, page_size=8,
                           quantized=quantized),
        prefill_buckets=(16,), max_context=max_context,
        prefix_cache=prefix)


def _engine_factory():
    return _engine(pages=64, slots=2, prefix=False)


def _role_fleet(roles):
    return serving.local_serving_fleet(len(roles), _engine_factory,
                                       warm=False, roles=roles)


def _close(pool, srv):
    for h in pool.replicas():
        try:
            h.close()
        except Exception:  # noqa: BLE001 — killed handles
            pass
    srv.close()


def _ref(prompt, n):
    return MODEL.reference_decode(PARAMS, list(prompt), n)


def _counter(name):
    fam = telemetry.registry().get(name)
    if fam is None:
        return 0.0
    return float(sum(ch.value for ch in fam.children().values()))


# ---------------------------------------------------------------------------
# chain hashing
# ---------------------------------------------------------------------------
def test_chain_hash_page_aligned_prefix_property():
    """One digest per FULL page-size block; the chain of an extended
    prompt starts with the chain of its prefix (the lookup walks this);
    any token change flips every digest from that block on; position
    folds in through the chain (a repeated block hashes differently at
    each offset)."""
    cache = PagedKVCache(1, 2, 8, num_pages=16, page_size=8)
    idx = PrefixIndex(cache)
    assert len(idx.chain(BASE)) == 2
    assert len(idx.chain(BASE + [9, 9, 9])) == 2   # partial block: none
    assert idx.chain(BASE + list(range(20, 28)))[:2] == idx.chain(BASE)
    mutated = [99] + BASE[1:]
    assert idx.chain(mutated)[0] != idx.chain(BASE)[0]
    rep = [5] * 16
    assert idx.chain(rep)[0] != idx.chain(rep)[1]


# ---------------------------------------------------------------------------
# refcount invariants (the pool-side half of sharing)
# ---------------------------------------------------------------------------
def test_shared_page_survives_owner_free():
    cache = PagedKVCache(1, 2, 8, num_pages=8, page_size=8)
    assert cache.reserve("a", 16)
    pa = [cache.alloc_page("a"), cache.alloc_page("a")]
    # b admits sharing a's first page
    assert cache.reserve("b", 16, shared=pa[:1])
    cache.alloc_for("b", 16)
    assert cache.refcount(pa[0]) == 2
    in_use = cache.pages_in_use()
    cache.free("a")
    # the shared page survived; only a's private page returned
    assert cache.refcount(pa[0]) == 1
    assert cache.pages_in_use() == in_use - 1
    assert pa[0] in cache.pages_of("b")
    cache.free("b")  # last reference: everything returns
    assert cache.pages_in_use() == 0
    assert cache.refcount(pa[0]) == 0


def test_retain_release_and_stale_shared_reserve():
    cache = PagedKVCache(1, 2, 8, num_pages=8, page_size=8)
    assert cache.reserve("a", 16)
    pa = cache.alloc_for("a", 16)
    cache.retain_pages(pa)              # index pin
    cache.free("a")
    assert cache.pages_in_use() == 2    # pinned pages stay resident
    assert cache.release_pages(pa) == 2
    assert cache.pages_in_use() == 0
    with pytest.raises(MXNetError):
        cache.retain_pages([pa[0]])     # non-resident: typed refusal
    with pytest.raises(MXNetError):
        cache.reserve("c", 16, shared=[pa[0]])  # stale index entry


def test_cow_page_bookkeeping_and_debt():
    cache = PagedKVCache(1, 2, 8, num_pages=8, page_size=8)
    assert cache.reserve("a", 16)
    pa = cache.alloc_for("a", 16)
    cache.retain_pages(pa)
    c0 = _counter("mxt_serving_cow_copies_total")
    # b fully shares a's pages and owes one divergence page
    assert cache.reserve("b", 16, shared=pa, cow=1)
    src, dst = cache.cow_page("b", 1)
    assert src == pa[1] and dst not in pa
    assert cache.pages_of("b") == [pa[0], dst]
    assert cache.refcount(src) == 2     # a + index pin keep it
    assert cache.refcount(dst) == 1
    assert _counter("mxt_serving_cow_copies_total") == c0 + 1
    # the COW debt is retired: no outstanding promise inflates the bill
    avail = cache.available()
    cache.free("b")
    assert cache.available() == avail + 1


def test_defrag_mover_remap_unit():
    """Defrag liveness is the refcount map: a pinned page owned by NO
    sequence compacts down (never into the free list) and registered
    movers see the remapping."""
    cache = PagedKVCache(1, 2, 8, num_pages=16, page_size=8)
    assert cache.reserve("a", 24)
    pa = cache.alloc_for("a", 24)
    cache.retain_pages(pa[2:])          # pin only the HIGH page
    cache.free("a")
    assert cache.pages_in_use() == 1
    seen = []
    cache.add_mover(seen.append)
    moved = cache.defrag()
    assert moved == 1 and seen and pa[2] in seen[0]
    new = seen[0][pa[2]]
    assert cache.refcount(new) == 1
    assert cache.release_pages([new]) == 1
    assert cache.pages_in_use() == 0


def test_defrag_remaps_prefix_index():
    """An index entry's pages survive an engine defrag (the index rides
    the mover callback) — a hit afterwards still decodes token-exactly."""
    eng = _engine(pages=16)
    pv = eng.admit(0, "a", BASE, 4)
    int(pv.get().reshape(-1)[0])
    eng.release(0)                       # pages survive as index pins
    assert eng.cache.pages_in_use() == 2
    eng.defrag()
    prompt = BASE + [20, 21]
    pv = eng.admit(0, "b", prompt, 4)
    t0 = int(pv.get().reshape(-1)[0])
    assert _counter("mxt_serving_prefix_hits_total") >= 1
    assert t0 == _ref(prompt, 1)[0]
    eng.release(0)


# ---------------------------------------------------------------------------
# token-exact reuse vs the cache-free oracle
# ---------------------------------------------------------------------------
def test_prefix_reuse_token_exact_vs_oracle():
    """A cold miss, a full-match replay (COW), a partial hit, and an
    unrelated prompt all decode token-exactly vs the dense cache-free
    oracle — reuse changes the page bill, never the tokens."""
    eng = _engine(pages=64, slots=2)
    sched = ContinuousBatcher(eng)
    prompts = [BASE + [20, 21, 22],      # cold miss (registers BASE)
               list(BASE),               # full match -> COW last page
               BASE + [30, 31],          # partial hit: 2 shared pages
               [40, 41, 42]]             # unrelated short miss
    h0 = _counter("mxt_serving_prefix_hits_total")
    c0 = _counter("mxt_serving_cow_copies_total")
    reqs = [sched.submit(Request(p, max_new_tokens=5)) for p in prompts]
    sched.run()
    for r, p in zip(reqs, prompts):
        assert r.state == "completed"
        assert r.output_tokens == _ref(p, 5), p
    assert _counter("mxt_serving_prefix_hits_total") >= h0 + 2
    assert _counter("mxt_serving_cow_copies_total") >= c0 + 1
    # every sequence released; only index pins keep pages resident
    eng.prefix.clear()
    assert eng.cache.pages_in_use() == 0


def test_full_match_cow_pages_diverge():
    eng = _engine(pages=32)
    pv = eng.admit(0, "a", BASE, 4)
    ta = int(pv.get().reshape(-1)[0])
    pa = eng.cache.pages_of("a")
    pv = eng.admit(1, "b", BASE, 4)
    tb = int(pv.get().reshape(-1)[0])
    pb = eng.cache.pages_of("b")
    assert ta == tb == _ref(BASE, 1)[0]
    assert pb[0] == pa[0]                # head page shared
    assert pb[-1] != pa[-1]              # tail page copy-on-written
    assert eng.cache.refcount(pa[0]) >= 3  # a + b + index pins
    eng.release(0)
    eng.release(1)


def test_quantized_cow_carries_pages_and_scales():
    """COW on the int8 pool copies BOTH the quantized rows and the f32
    amax planes: the diverged page must be bit-identical to its source
    (the re-prefilled tail token re-quantizes to the same values — one
    layer, same inputs)."""
    eng = _engine(pages=32, quantized=True)
    pv = eng.admit(0, "a", BASE, 4)
    pv.get()
    pv = eng.admit(1, "b", BASE, 4)
    pv.get()
    src = eng.cache.pages_of("a")[-1]
    dst = eng.cache.pages_of("b")[-1]
    assert src != dst
    np.testing.assert_array_equal(
        np.asarray(eng.cache.k_pages[:, dst]),
        np.asarray(eng.cache.k_pages[:, src]))
    np.testing.assert_array_equal(
        np.asarray(eng.cache.k_scales[:, dst]),
        np.asarray(eng.cache.k_scales[:, src]))
    np.testing.assert_array_equal(
        np.asarray(eng.cache.v_scales[:, dst]),
        np.asarray(eng.cache.v_scales[:, src]))
    eng.release(0)
    eng.release(1)


def test_can_admit_prefix_discount_and_lru_shedding():
    """A cached prefix discounts the admission page bill below what a
    raw reservation could afford; under pool pressure cold index
    entries shed LRU to free pages — index pins are capacity, not a
    leak."""
    eng = _engine(pages=6, max_context=48)
    pv = eng.admit(0, "a", BASE, 8)      # 3 of 6 pages
    int(pv.get().reshape(-1)[0])
    eng.release(0)                       # 2 full pages stay index-pinned
    assert eng.cache.pages_in_use() == 2
    # squeeze the pool: 2 more pages held by a foreign reservation
    assert eng.cache.reserve("pin", 16)
    eng.cache.alloc_for("pin", 16)       # free pages: 2
    total = len(BASE) + 8                # 3-page bill undiscounted
    assert not eng.cache.can_reserve(total)
    # full match: 2 shared + 1 COW = 2 fresh-page bill -> fits
    assert eng.can_admit(total, prompt=BASE)
    assert len(eng.prefix) == 2          # the hit kept its entries
    # an UNRELATED same-size prompt only fits once the index sheds
    assert eng.can_admit(total, prompt=list(range(30, 46)))
    assert len(eng.prefix) == 0          # entries shed LRU
    assert eng.cache.pages_in_use() == 2  # only the pin remains
    eng.cache.free("pin")


# ---------------------------------------------------------------------------
# the async contract is untouched
# ---------------------------------------------------------------------------
def test_zero_host_sync_decode_with_prefix_hits():
    """Prefix reuse is an ADMISSION feature: with a shared-prefix hit
    resident, the decode loop still performs <= 1 host sync per K
    steps — sync parity with the plain engine."""
    eng = _engine(pages=64, slots=2)
    sched = ContinuousBatcher(eng)
    h0 = _counter("mxt_serving_prefix_hits_total")
    sched.submit(Request(list(BASE), max_new_tokens=40))
    sched.submit(Request(list(BASE), max_new_tokens=40))  # COW hit
    for _ in range(4):                    # admit + absorb prefill reads
        sched.step()
    assert _counter("mxt_serving_prefix_hits_total") >= h0 + 1
    with eng_mod.bulk(4):
        s0 = profiler.host_sync_count()
        for _ in range(12):
            sched.step()
        syncs = profiler.host_sync_count() - s0
    assert syncs <= 12 // 4 + 1, \
        "prefix-hit decode loop performed %d host syncs over 12 steps" \
        % syncs
    sched.run()


# ---------------------------------------------------------------------------
# disaggregated prefill/decode over the fleet transport
# ---------------------------------------------------------------------------
def test_disagg_handoff_token_exact_and_trace_chain():
    """Long prompt on a role-split pool: prefilled on the prefill tier,
    pages shipped, adopted and decoded on a decode replica — output
    token-exact vs the oracle; the prefill->ship->adopt->decode chain
    reconstructs from the trace_id alone and exports to Chrome
    trace-event JSON. A short prompt routes straight to the decode
    tier with no ship."""
    pool, srv = _role_fleet(["prefill", "decode", "decode"])
    router = FleetRouter(pool, prefill_threshold=8)
    s0 = _counter("mxt_serving_pages_shipped_total")
    a0 = _counter("mxt_serving_pages_adopted_total")
    long = router.submit(list(range(1, 13)), max_new_tokens=5,
                         token="dg-long")
    short = router.submit([5, 9, 2], max_new_tokens=4, token="dg-short")
    router.run(max_steps=2000)
    assert long.state == "completed" and short.state == "completed"
    assert long.result == _ref(long.prompt, 5)
    assert short.result == _ref(short.prompt, 4)
    assert long.committed_by in (1, 2)    # decode tier decoded it
    assert short.committed_by in (1, 2)
    assert _counter("mxt_serving_pages_shipped_total") == s0 + 2
    assert _counter("mxt_serving_pages_adopted_total") == a0 + 2
    assert _counter("mxt_serving_ship_bytes_total") > 0
    # the handoff chain, reassembled from the trace id alone
    tree = trace_tree(telemetry.trace_spans(), long.trace_id)
    names = set(tree["names"])
    assert {"prefill", "ship", "adopt", "dispatch", "decode",
            "commit"} <= names
    assert "replica-0" in tree["tracks"]  # prefill ran on the P tier
    ships = [s for s in tree["tracks"]["router"] if s["name"] == "ship"]
    assert ships and ships[0]["attrs"]["replica"] == 0
    assert ships[0]["attrs"]["pages"] == 2
    # the short request never shipped
    assert "ship" not in trace_tree(telemetry.trace_spans(),
                                    short.trace_id)["names"]
    # Perfetto-loadable chrome trace: events carry the required keys
    doc = chrome_trace(telemetry.trace_spans(long.trace_id))
    evs = doc["traceEvents"]
    assert any(e.get("name") == "ship" and e.get("ph") == "X"
               for e in evs)
    assert all(set(e) >= {"name", "ph", "pid", "tid", "ts"}
               for e in evs)
    # adopted state fully released once decoding finished
    for h in pool.replicas():
        assert h.engine.cache.pages_in_use() == 0
    _close(pool, srv)


def test_ship_idempotent_and_adopt_idempotent():
    """A re-shipped copy id returns the CACHED payload (no second
    prefill); a re-adopted copy id resolves to the already-submitted
    request — so the router's kv_retry can replay either half of the
    handoff safely."""
    pool, srv = _role_fleet(["prefill", "decode"])
    pf, dec = pool.get(0), pool.get(1)
    prompt = list(range(1, 13))
    tok0, payload = pf.ship_pages("cid-1", prompt, 4)
    tok0b, payload_b = pf.ship_pages("cid-1", prompt, 4)
    assert tok0b == tok0 and payload_b is payload
    assert pf.engine.cache.pages_in_use() == 0  # shipped state released
    state = dec.adopt_copy("cid-1", prompt, 4, handoff=(tok0, payload))
    state2 = dec.adopt_copy("cid-1", prompt, 4, handoff=(tok0, payload))
    assert state == state2
    assert len(dec._copies) == 1
    done = []
    for _ in range(400):
        dec.tick(time.monotonic())
        done = dec.poll()
        if done:
            break
    (cid, st, toks), = done
    assert cid == "cid-1" and st == "completed"
    assert toks == _ref(prompt, 4)
    _close(pool, srv)


def test_adopt_refuses_pool_dtype_mismatch():
    eng_q = _engine(pages=32, quantized=True, prefix=False)
    eng_f = _engine(pages=32, prefix=False)
    pv = eng_f.admit(0, "s", list(range(1, 13)), 4)
    int(pv.get().reshape(-1)[0])
    payload = eng_f.export_pages("s")
    eng_f.release(0)
    with pytest.raises(MXNetError):
        eng_q.adopt(0, "t", 12, 4, payload, 7)
    assert eng_q.cache.pages_in_use() == 0


# ---------------------------------------------------------------------------
# chaos cell (swept per seed by tools/chaos_matrix.sh)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_chaos_prefill_replica_killed_mid_ship(monkeypatch):
    """Seeded replica_kill of a prefill replica: the router marks it
    dead and re-ships from the surviving prefill replica — and when the
    prefill tier is GONE, falls back to local prefill on the decode
    tier. Either way zero requests are lost, outputs are token-exact,
    and no surviving replica leaks pages."""
    from mxnet_tpu import resilience

    # phase 1: a prefill survivor takes over
    monkeypatch.setenv(
        "MXT_FAULT",
        "replica_kill:replica=0,after=0,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    try:
        pool, srv = _role_fleet(["prefill", "prefill", "decode"])
        router = FleetRouter(pool, prefill_threshold=8)
        rng = np.random.RandomState(_seed())
        reqs = [router.submit(rng.randint(1, 64, 12).tolist(),
                              max_new_tokens=6, token="cp%d" % i)
                for i in range(4)]
        router.run(max_steps=2000)
        assert pool.get(0).state == "dead"
        assert all(rr.state == "completed" for rr in reqs)
        assert all(rr.result == _ref(rr.prompt, rr.max_new_tokens)
                   for rr in reqs)
        assert all(rr.committed_by == 2 for rr in reqs)  # decode tier
        for h in pool.replicas():
            if h.state != "dead":
                assert h.engine.cache.pages_in_use() == 0
        _close(pool, srv)
    finally:
        resilience.reset_faults()

    # phase 2: the ONLY prefill replica dies -> local-prefill fallback
    # on the decode tier; the request still completes
    monkeypatch.setenv(
        "MXT_FAULT",
        "replica_kill:replica=0,after=0,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    try:
        pool, srv = _role_fleet(["prefill", "decode"])
        router = FleetRouter(pool, prefill_threshold=8)
        rr = router.submit(list(range(1, 13)), max_new_tokens=6,
                           token="cpf")
        router.run(max_steps=2000)
        assert pool.get(0).state == "dead"
        assert rr.state == "completed"
        assert rr.result == _ref(rr.prompt, 6)
        assert rr.committed_by == 1       # local prefill + decode
        assert pool.get(1).engine.cache.pages_in_use() == 0
        _close(pool, srv)
    finally:
        resilience.reset_faults()


# ---------------------------------------------------------------------------
# observability + lint
# ---------------------------------------------------------------------------
def test_mxt_top_prefix_line():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    samples = {
        ("mxt_serving_tokens_total", frozenset()): 120,
        ("mxt_serving_prefix_hits_total", frozenset()): 30,
        ("mxt_serving_prefix_misses_total", frozenset()): 10,
        ("mxt_serving_shared_pages", frozenset()): 6,
        ("mxt_serving_cow_copies_total", frozenset()): 2,
    }
    frame = mod.render(samples, None, 0)
    assert "prefix" in frame and "0.750" in frame
    # a replica without the prefix cache renders no prefix noise
    plain = mod.render({("mxt_serving_tokens_total", frozenset()): 5},
                       None, 0)
    assert "prefix" not in plain


def test_host_sync_lint_covers_prefix_and_handoff():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert "mxnet_tpu/serving/prefix.py" in m.SCAN
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = [b for b in m.check(root)
           if b[0].startswith("mxnet_tpu/serving/")]
    assert not bad, bad
