"""Reference binary checkpoint format (ref: src/ndarray/ndarray.cc —
NDArray::Save/Load; c_api.cc — MXNDArraySave).  Round-trips, a
hand-synthesized golden-bytes fixture in the exact reference layout, and
the Module/Gluon checkpoint surfaces on top of it."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import sparse
from mxnet_tpu.ndarray import mx_binary


# ---------------------------------------------------------------- helpers
def synth_dense_record(arr, magic=0xF993FAC9):
    """Reference V2 dense record, built independently of mx_binary's
    writer (golden bytes — byte-layout oracle)."""
    out = [struct.pack("<I", magic), struct.pack("<i", 0)]
    out.append(struct.pack("<I", arr.ndim))
    out.append(struct.pack("<%dq" % arr.ndim, *arr.shape))
    out.append(struct.pack("<ii", 1, 0))
    flag = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
            "int32": 4, "int8": 5, "int64": 6}[arr.dtype.name]
    out.append(struct.pack("<i", flag))
    out.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(out)


def synth_file(records, names):
    out = [struct.pack("<QQQ", 0x112, 0, len(records))]
    out.extend(records)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        out.append(struct.pack("<Q", len(b)) + b)
    return b"".join(out)


# ---------------------------------------------------------------- golden
def test_golden_reference_file_loads(tmp_path):
    """A file in the reference byte layout (synthesized by an independent
    writer above) parses through mx.nd.load."""
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.array([1.5, -2.0], dtype=np.float32)
    path = tmp_path / "golden.params"
    path.write_bytes(synth_file(
        [synth_dense_record(w), synth_dense_record(b)],
        ["arg:fc_weight", "arg:fc_bias"]))
    loaded = nd.load(str(path))
    assert set(loaded) == {"arg:fc_weight", "arg:fc_bias"}
    np.testing.assert_array_equal(loaded["arg:fc_weight"].asnumpy(), w)
    np.testing.assert_array_equal(loaded["arg:fc_bias"].asnumpy(), b)


def test_golden_bytes_writer_matches_layout(tmp_path):
    """Our writer's bytes == the independent synthesizer's bytes."""
    w = np.arange(6, dtype=np.float32).reshape(2, 3)
    ours = mx_binary.dumps([nd.array(w)], ["arg:w"])
    theirs = synth_file([synth_dense_record(w)], ["arg:w"])
    assert ours == theirs


def test_golden_list_no_names(tmp_path):
    a = np.array([7], dtype=np.int64)
    path = tmp_path / "list.nd"
    path.write_bytes(synth_file([synth_dense_record(a)], []))
    loaded = nd.load(str(path))
    assert isinstance(loaded, list) and len(loaded) == 1
    np.testing.assert_array_equal(loaded[0].asnumpy(), a)


def test_v1_and_legacy_records_load(tmp_path):
    """Pre-V2 records: V1 (int64 shape, no stype) and legacy (uint32
    ndim-first)."""
    a = np.arange(4, dtype=np.float32)
    v1 = (struct.pack("<I", 0xF993FAC8) + struct.pack("<I", 1) +
          struct.pack("<q", 4) + struct.pack("<ii", 1, 0) +
          struct.pack("<i", 0) + a.tobytes())
    legacy = (struct.pack("<I", 1) + struct.pack("<I", 4) +
              struct.pack("<ii", 1, 0) + struct.pack("<i", 0) +
              a.tobytes())
    path = tmp_path / "old.nd"
    path.write_bytes(synth_file([v1, legacy], []))
    loaded = nd.load(str(path))
    for item in loaded:
        np.testing.assert_array_equal(item.asnumpy(), a)


# ------------------------------------------------------------ round-trips
@pytest.mark.parametrize("dtype", ["float32", "float64", "float16",
                                   "uint8", "int32", "int8", "int64"])
def test_roundtrip_dtypes(tmp_path, dtype):
    a = (np.random.RandomState(0).uniform(0, 50, (3, 5))).astype(dtype)
    p = str(tmp_path / "a.nd")
    nd.save(p, {"x": nd.array(a)})
    back = nd.load(p)["x"]
    assert back.asnumpy().dtype == np.dtype(dtype)
    np.testing.assert_array_equal(back.asnumpy(), a)


def test_roundtrip_bf16(tmp_path):
    x = nd.array(np.linspace(-3, 3, 16).reshape(4, 4)).astype("bfloat16")
    p = str(tmp_path / "bf16.nd")
    nd.save(p, [x])
    back = nd.load(p)[0]
    assert "bfloat16" in str(back.asnumpy().dtype)
    np.testing.assert_array_equal(
        back.asnumpy().astype(np.float32), x.asnumpy().astype(np.float32))


def test_roundtrip_scalar_and_empty_name_unicode(tmp_path):
    p = str(tmp_path / "s.nd")
    nd.save(p, {"héllo/λ": nd.array(np.float32(3.25).reshape(()))})
    back = nd.load(p)
    assert list(back) == ["héllo/λ"]
    assert back["héllo/λ"].asnumpy().shape == ()


def test_roundtrip_row_sparse(tmp_path):
    vals = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    idx = np.array([0, 2, 5], dtype=np.int64)
    rs = sparse.row_sparse_array((vals, idx), shape=(8, 4))
    p = str(tmp_path / "rs.nd")
    nd.save(p, {"emb": rs})
    back = nd.load(p)["emb"]
    assert isinstance(back, sparse.RowSparseNDArray)
    assert back.shape == (8, 4)
    np.testing.assert_array_equal(back.data.asnumpy(), vals)
    np.testing.assert_array_equal(back.indices.asnumpy(), idx)


def test_roundtrip_csr(tmp_path):
    data = np.array([1., 2., 3.], dtype=np.float32)
    indices = np.array([1, 0, 2], dtype=np.int64)
    indptr = np.array([0, 1, 1, 3], dtype=np.int64)
    cs = sparse.csr_matrix((data, indices, indptr), shape=(3, 4))
    p = str(tmp_path / "csr.nd")
    nd.save(p, [cs])
    back = nd.load(p)[0]
    assert isinstance(back, sparse.CSRNDArray)
    np.testing.assert_array_equal(back.todense().asnumpy(),
                                  cs.todense().asnumpy())


def test_npz_fallback_still_loads(tmp_path):
    """Files written by pre-r5 rounds (npz) keep loading."""
    p = str(tmp_path / "old.npz")
    np.savez(open(p, "wb"), **{"w": np.ones((2, 2), np.float32)})
    back = nd.load(p)
    np.testing.assert_array_equal(back["w"].asnumpy(), np.ones((2, 2)))


def test_truncated_file_raises(tmp_path):
    w = np.ones((4, 4), np.float32)
    full = mx_binary.dumps([nd.array(w)], ["w"])
    p = tmp_path / "trunc.nd"
    p.write_bytes(full[:len(full) // 2])
    with pytest.raises(mx.base.MXNetError):
        nd.load(str(p))


# ---------------------------------------------------- checkpoint surfaces
def test_module_checkpoint_via_binary_format(tmp_path):
    """Module.save_checkpoint emits reference-layout files; a synthesized
    reference .params + -symbol.json pair loads through
    Module.load_checkpoint."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.module import Module

    x = sym.Variable("data")
    net = sym.FullyConnected(x, num_hidden=3, name="fc1")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = Module(net, data_names=["data"], label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", (2, 5))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3)

    params_file = prefix + "-0003.params"
    head = open(params_file, "rb").read(8)
    assert mx_binary.is_mx_binary(head), \
        "checkpoint is not in the reference binary format"

    # synthesize the same .params independently and load it back
    arg, aux = mod.get_params()
    records, names = [], []
    for k, v in arg.items():
        records.append(synth_dense_record(
            v.asnumpy().astype(np.float32)))
        names.append("arg:" + k)
    synth = tmp_path / "synth-0001.params"
    synth.write_bytes(synth_file(records, names))
    import shutil
    shutil.copy(prefix + "-symbol.json", str(tmp_path / "synth-symbol.json"))
    sym2, arg2, aux2 = mx.model.load_checkpoint(str(tmp_path / "synth"), 1)
    assert set(arg2) == set(arg)
    for k in arg:
        np.testing.assert_allclose(arg2[k].asnumpy(), arg[k].asnumpy(),
                                   rtol=1e-6)


def test_symbolblock_loads_reference_params(tmp_path):
    """SymbolBlock.imports over a reference-format pair (gluon surface)."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.gluon import SymbolBlock

    x = sym.Variable("data")
    net = sym.FullyConnected(x, num_hidden=4, name="fc0")
    net.save(str(tmp_path / "m-symbol.json"))
    w = np.random.RandomState(2).randn(4, 6).astype(np.float32)
    b = np.zeros(4, np.float32)
    (tmp_path / "m-0000.params").write_bytes(synth_file(
        [synth_dense_record(w), synth_dense_record(b)],
        ["arg:fc0_weight", "arg:fc0_bias"]))
    blk = SymbolBlock.imports(str(tmp_path / "m-symbol.json"), ["data"],
                              str(tmp_path / "m-0000.params"))
    out = blk(mx.nd.array(np.ones((2, 6), np.float32)))
    np.testing.assert_allclose(out.asnumpy(), np.ones((2, 6)) @ w.T + b,
                               rtol=1e-5)


def test_gluon_save_load_parameters_binary(tmp_path):
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    p = str(tmp_path / "dense.params")
    net.save_parameters(p)
    assert mx_binary.is_mx_binary(open(p, "rb").read(8))
    net2 = nn.Dense(3, in_units=4)
    net2.load_parameters(p)
    np.testing.assert_array_equal(net.weight.data().asnumpy(),
                                  net2.weight.data().asnumpy())


def test_v1_uninitialized_slot_then_valid_record(tmp_path):
    """A V1 ndim-0 (uninitialized) record carries no context/dtype/blob;
    the parser must not consume the following record's bytes."""
    a = np.arange(4, dtype=np.float32)
    v1_none = struct.pack("<I", 0xF993FAC8) + struct.pack("<I", 0)
    v1_ok = (struct.pack("<I", 0xF993FAC8) + struct.pack("<I", 1) +
             struct.pack("<q", 4) + struct.pack("<ii", 1, 0) +
             struct.pack("<i", 0) + a.tobytes())
    path = tmp_path / "v1none.nd"
    path.write_bytes(synth_file([v1_none, v1_ok], []))
    loaded = nd.load(str(path))
    assert loaded[0].shape == (0,)
    np.testing.assert_array_equal(loaded[1].asnumpy(), a)
