"""Symbol / Executor / Module tests (modeled on
tests/python/unittest/{test_symbol,test_module}.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter, DataBatch, DataDesc
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def _mlp_sym(hidden=16, classes=10):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_symbol_compose_and_listing():
    out = _mlp_sym()
    assert out.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert out.list_outputs() == ["softmax_output"]
    assert out.name == "softmax"
    internals = out.get_internals()
    assert "relu1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_symbol_infer_shape():
    out = _mlp_sym(hidden=32, classes=7)
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(6, 20))
    args = out.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (32, 20)
    assert d["fc1_bias"] == (32,)
    assert d["fc2_weight"] == (7, 32)
    assert d["softmax_label"] == (6,)
    assert out_shapes == [(6, 7)]
    assert aux_shapes == []


def test_symbol_infer_shape_conv_bn():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          name="conv1")
    net = sym.BatchNorm(net, name="bn1")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max",
                      name="pool1")
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["bn1_gamma"] == (8,)
    assert aux_shapes == [(8,), (8,)]
    assert out_shapes == [(2, 8, 4, 4)]
    assert net.list_auxiliary_states() == ["bn1_moving_mean",
                                           "bn1_moving_var"]


def test_symbol_json_roundtrip(tmp_path):
    out = _mlp_sym()
    f = str(tmp_path / "net.json")
    out.save(f)
    loaded = sym.load(f)
    assert loaded.list_arguments() == out.list_arguments()
    assert loaded.list_outputs() == out.list_outputs()
    a1, o1, _ = out.infer_shape(data=(3, 5))
    a2, o2, _ = loaded.infer_shape(data=(3, 5))
    assert a1 == a2 and o1 == o2


def test_symbol_arithmetic_and_methods():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b) * 2.0 - a / b
    ex = c.bind(args={"a": nd.array([2.0, 4.0]), "b": nd.array([1.0, 2.0])})
    out = ex.forward()[0].asnumpy()
    assert_almost_equal(out, np.array([4.0, 10.0]))
    d = a.exp()
    ex2 = d.bind(args={"a": nd.array([0.0, 1.0])})
    assert_almost_equal(ex2.forward()[0], np.exp([0.0, 1.0]), rtol=1e-5)


@with_seed()
def test_executor_forward_backward_matches_autograd():
    """Symbolic grads must equal imperative autograd grads."""
    from mxnet_tpu import autograd as ag

    B, D, H, C = 4, 6, 8, 5
    rng = np.random.RandomState(0)
    w1 = rng.normal(0, 0.1, (H, D)).astype("f4")
    b1 = np.zeros(H, "f4")
    w2 = rng.normal(0, 0.1, (C, H)).astype("f4")
    b2 = np.zeros(C, "f4")
    x = rng.normal(size=(B, D)).astype("f4")
    y = rng.randint(0, C, B).astype("f4")

    out = _mlp_sym(hidden=H, classes=C)
    ex = out.simple_bind(data=(B, D))
    ex.copy_params_from({"fc1_weight": w1, "fc1_bias": b1,
                         "fc2_weight": w2, "fc2_bias": b2},
                        allow_extra_params=True)
    ex.forward(is_train=True, data=x, softmax_label=y)
    ex.backward()
    sym_grad = ex.grad_dict["fc1_weight"].asnumpy()

    # imperative reference
    w1_nd = nd.array(w1)
    w1_nd.attach_grad()
    with ag.record():
        h = nd.relu(nd.FullyConnected(nd.array(x), w1_nd, nd.array(b1),
                                      num_hidden=H))
        logits = nd.FullyConnected(h, nd.array(w2), nd.array(b2),
                                   num_hidden=C)
        prob = nd.SoftmaxOutput(logits, nd.array(y))
    prob.backward()
    assert_almost_equal(sym_grad, w1_nd.grad.asnumpy(), rtol=1e-4,
                        atol=1e-5)


@with_seed()
def test_executor_grad_req_add_and_null():
    x_s = sym.Variable("x")
    out = sym.MakeLoss(x_s * x_s)
    ex = out.bind(args={"x": nd.array([1.0, 2.0])},
                  grad_req="add")
    ex.forward(is_train=True)
    ex.backward()
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(ex.grad_dict["x"], np.array([4.0, 8.0]), rtol=1e-5)

    ex2 = out.bind(args={"x": nd.array([1.0, 2.0])}, grad_req="null")
    ex2.forward(is_train=True)
    ex2.backward()  # no-op
    assert ex2.grad_dict == {}


@with_seed()
def test_module_fit_mlp():
    """Module.fit on a separable problem reaches high accuracy."""
    rng = np.random.RandomState(0)
    n = 200
    X = rng.normal(size=(n, 10)).astype("f4")
    w_true = rng.normal(size=(10,)).astype("f4")
    Y = (X @ w_true > 0).astype("f4")
    it = NDArrayIter(X, Y, batch_size=20, shuffle=True)

    out = _mlp_sym(hidden=16, classes=2)
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=12, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            eval_metric="acc",
            initializer=mx.init.Xavier())
    score = mod.score(it, "acc")
    assert score[0][1] > 0.93, score


@with_seed()
def test_module_predict_and_checkpoint(tmp_path):
    rng = np.random.RandomState(1)
    X = rng.normal(size=(30, 6)).astype("f4")
    Y = rng.randint(0, 3, 30).astype("f4")
    it = NDArrayIter(X, Y, batch_size=10)
    out = _mlp_sym(hidden=8, classes=3)
    mod = mx.mod.Module(out)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    preds = mod.predict(it)
    assert preds.shape == (30, 3)

    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    sym2, arg, aux = mx.model.load_checkpoint(prefix, 3)
    assert sym2.list_arguments() == out.list_arguments()

    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    it.reset()
    preds2 = mod2.predict(it)
    assert_almost_equal(preds, preds2.asnumpy(), rtol=1e-5)


@with_seed()
def test_module_batchnorm_aux_updates():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    net = sym.BatchNorm(net, name="bn", momentum=0.5)
    net = sym.MakeLoss(net, name="loss")
    mod = mx.mod.Module(net, label_names=())
    mod.bind(data_shapes=[("data", (8, 6))])
    mod.init_params(initializer=mx.init.Xavier())
    mean0 = mod._exec.aux_dict["bn_moving_mean"].asnumpy().copy()
    batch = DataBatch(data=[nd.array(
        np.random.RandomState(0).normal(2.0, 1.0, (8, 6)).astype("f4"))])
    mod.forward(batch, is_train=True)
    mean1 = mod._exec.aux_dict["bn_moving_mean"].asnumpy()
    assert np.abs(mean1 - mean0).sum() > 0  # running stats moved
    mod.forward(batch, is_train=False)
    mean2 = mod._exec.aux_dict["bn_moving_mean"].asnumpy()
    assert_almost_equal(mean1, mean2)  # inference does not move them


@with_seed()
def test_bucketing_module():
    """Per-bucket executors share parameters (ref test_module.py)."""
    buckets = [4, 8]

    def sym_gen(seq_len):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=6, name="fc",
                                 flatten=False)
        net = sym.mean(net, axis=1, name="pool")
        net = sym.FullyConnected(net, num_hidden=2, name="out")
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[DataDesc("data", (2, 8, 3))],
             label_shapes=[DataDesc("softmax_label", (2,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})

    rng = np.random.RandomState(0)
    for seq_len in [8, 4, 8, 4]:
        batch = DataBatch(
            data=[nd.array(rng.normal(size=(2, seq_len, 3)).astype("f4"))],
            label=[nd.array(np.array([0.0, 1.0], "f4"))],
            bucket_key=seq_len,
            provide_data=[DataDesc("data", (2, seq_len, 3))],
            provide_label=[DataDesc("softmax_label", (2,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # both buckets exist and share the same weight values
    w4 = mod._buckets[4]._exec.arg_dict["fc_weight"].asnumpy()
    w8 = mod._buckets[8]._exec.arg_dict["fc_weight"].asnumpy()
    assert_almost_equal(w4, w8)


@with_seed()
def test_symbol_block_and_export(tmp_path):
    """HybridBlock → export → SymbolBlock.imports roundtrip."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        net.add(nn.Dense(12, activation="relu", in_units=6))
        net.add(nn.BatchNorm(in_channels=12))
        net.add(nn.Dense(3, in_units=12))
    net.initialize()
    x = nd.random.uniform(shape=(5, 6))
    y0 = net(x).asnumpy()

    path = str(tmp_path / "mlp")
    sym_file, param_file = net.export(path, epoch=7)
    loaded = gluon.SymbolBlock.imports(sym_file, ["data"], param_file)
    y1 = loaded(x).asnumpy()
    assert_almost_equal(y0, y1, rtol=1e-5, atol=1e-6)


@with_seed()
def test_symbol_block_gradients():
    from mxnet_tpu import autograd as ag
    from mxnet_tpu import gluon

    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    blk = gluon.SymbolBlock(net, [data])
    blk.initialize()
    x = nd.random.uniform(shape=(2, 3))
    with ag.record():
        out = blk(x)
        loss = (out * out).sum()
    loss.backward()
    g = blk.params["fc_weight"].grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_infer_shape_raises_on_unknown():
    out = sym.FullyConnected(sym.Variable("data"), num_hidden=2)
    with pytest.raises(mx.MXNetError, match="cannot fully infer"):
        out.infer_shape()


def test_split_json_roundtrip_keeps_arity():
    parts = sym.split(sym.Variable("x"), num_outputs=3, axis=0, name="sp")
    loaded = sym.load_json(parts.tojson())
    assert loaded.list_outputs() == ["sp_output0", "sp_output1",
                                     "sp_output2"]
    ex = loaded.bind(args={"x": nd.array([[1.0], [2.0], [3.0]])})
    outs = ex.forward()
    assert len(outs) == 3
    assert_almost_equal(outs[2], np.array([[3.0]]))


def test_make_loss_valid_normalization():
    x = sym.Variable("x")
    out = sym.MakeLoss(x, normalization="valid", valid_thresh=0.0)
    data = nd.array([0.0, 0.0, 2.0, 3.0])  # 2 valid elements
    ex = out.bind(args={"x": data})
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(ex.grad_dict["x"],
                        np.full(4, 0.5, "f4"), rtol=1e-6)


def test_group_and_multi_output():
    a = sym.Variable("a")
    b = a * 2.0
    c = a + 1.0
    g = sym.Group([b, c])
    assert len(g.list_outputs()) == 2
    ex = g.bind(args={"a": nd.array([1.0, 2.0])})
    outs = ex.forward()
    assert_almost_equal(outs[0], np.array([2.0, 4.0]))
    assert_almost_equal(outs[1], np.array([2.0, 3.0]))
    parts = sym.split(sym.Variable("x"), num_outputs=2, axis=0)
    assert len(parts.list_outputs()) == 2
    first = parts[0]
    ex2 = first.bind(args={"x": nd.array([[1.0], [2.0]])})
    assert_almost_equal(ex2.forward()[0], np.array([[1.0]]))


def test_infer_type_mixed_dtypes():
    """infer_type propagates real dtypes (ref: nnvm InferType pass), not a
    blanket float32: explicit arg dtypes flow forward, Cast overrides, and
    promotion applies where shapes are unknown."""
    import numpy as np

    data = sym.Variable("data")
    w = sym.Variable("w")
    net = sym.FullyConnected(data=data, weight=w, no_bias=True,
                             num_hidden=8, name="fc")
    arg_t, out_t, _ = net.infer_type(data=np.float16, w=np.float16)
    names = net.list_arguments()
    assert dict(zip(names, arg_t))["data"] == np.dtype("float16")
    assert out_t[0] == np.dtype("float16")

    # defaults stay float32
    arg_t, out_t, _ = net.infer_type()
    assert all(t == np.dtype("float32") for t in arg_t)
    assert out_t[0] == np.dtype("float32")

    # Cast overrides regardless of input dtype
    casted = sym.Cast(net, dtype="bfloat16", name="c")
    _, out_t, _ = casted.infer_type(data=np.float16, w=np.float16)
    assert out_t[0] == np.dtype("bfloat16")

    # promotion when dtypes disagree (shape-free walk)
    mixed = data + w
    _, out_t, _ = mixed.infer_type(data=np.float16, w=np.float64)
    assert out_t[0] == np.dtype("float64")


def test_sequential_module_train():
    """SequentialModule chains modules; grads thread back through the
    chain (ref: module/sequential_module.py)."""
    import numpy as np
    from mxnet_tpu.io import DataBatch, DataDesc

    feat = sym.FullyConnected(sym.Variable("data"), num_hidden=8,
                              name="feat")
    feat = sym.Activation(feat, act_type="relu", name="feat_relu")
    head = sym.FullyConnected(sym.Variable("feat_relu_output"),
                              num_hidden=2, name="head")
    head = sym.SoftmaxOutput(head, name="softmax")

    mod = mx.mod.SequentialModule()
    mod.add(mx.mod.Module(feat, data_names=["data"], label_names=[]))
    mod.add(mx.mod.Module(head, data_names=["feat_relu_output"],
                          label_names=["softmax_label"]),
            take_labels=True)
    mod.bind(data_shapes=[DataDesc("data", (4, 6))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})

    rng = np.random.RandomState(0)
    x = nd.array(rng.normal(size=(4, 6)).astype("f4"))
    y = nd.array(np.array([0.0, 1.0, 0.0, 1.0], "f4"))
    metric = mx.metric.Accuracy()
    for _ in range(25):
        batch = DataBatch(data=[x], label=[y])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    mod.update_metric(metric, [y])
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (4, 2)
    # the chain actually learned the labels (grads crossed the boundary)
    assert (out.argmax(axis=1) == y.asnumpy()).all()
    # and the metric routed labels to the loss-bearing module
    assert metric.get()[1] == 1.0


def test_print_summary_and_plot_network():
    import pytest as _pytest

    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(data=net, num_hidden=2, name="fc2")
    out = mx.viz.print_summary(net, shape={"data": (4, 10)})
    assert "fc1" in out and "fc2" in out
    assert "Total params: %d" % (10 * 8 + 8 + 8 * 2 + 2) in out  # 106
    try:
        import graphviz  # noqa: F401
        dot = mx.viz.plot_network(net)
        assert "fc1" in dot.source
    except ImportError:
        with _pytest.raises(ImportError):
            mx.viz.plot_network(net)


def test_module_fit_converges():
    """Module.fit with default optimizer_params must actually learn — the
    reference defaults rescale_grad to 1/batch_size in init_optimizer
    (module.py); without it gradients arrive batch-summed and training
    diverges or stalls at chance accuracy."""
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=32, name="cfc1")
    h = sym.Activation(h, act_type="relu", name="crelu")
    h = sym.FullyConnected(h, num_hidden=10, name="cfc2")
    out = sym.SoftmaxOutput(h, name="softmax")

    rng = np.random.RandomState(0)
    protos = rng.rand(10, 20).astype("f4")
    y = rng.randint(0, 10, (512,))
    x = (protos[y] + rng.normal(0, 0.2, (512, 20))).astype("f4")
    it = mx.io.NDArrayIter(x, y.astype("f4"), 64, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            eval_metric="acc", num_epoch=4)
    assert mod._optimizer.rescale_grad == pytest.approx(1.0 / 64)
    it.reset()
    score = mod.score(it, mx.metric.Accuracy())
    assert score[0][1] > 0.9, score
