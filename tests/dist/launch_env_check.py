"""Worker script for the launcher env-plumbing test: asserts the rank /
coordinator / secret env contract tools/launch.py promises, then
completes one cross-process sync reduction to prove the rendezvous env
actually works end to end.

Run: python tools/launch.py -n 2 --launcher local \
         python tests/dist/launch_env_check.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def main():
    # -- env contract (satellite: launch_local plumbing was untested) --
    coord = os.environ["MXT_COORDINATOR"]
    host, _, port = coord.rpartition(":")
    assert host == "127.0.0.1" and int(port) > 0, coord
    n = int(os.environ["MXT_NUM_WORKERS"])
    rank = int(os.environ["MXT_WORKER_ID"])
    assert 0 <= rank < n, (rank, n)
    # reference-compatible spellings forwarded too
    assert os.environ["DMLC_NUM_WORKER"] == str(n)
    assert os.environ["DMLC_WORKER_ID"] == str(rank)
    assert os.environ["DMLC_ROLE"] == "worker"
    # secret forwarding: the launcher inherits the parent env wholesale
    want_secret = os.environ.get("LAUNCH_TEST_EXPECT_SECRET")
    if want_secret:
        assert os.environ.get("MXT_KVSTORE_SECRET") == want_secret, \
            "secret not forwarded to worker env"

    # -- one sync reduction through the launched rendezvous --
    # CPU processes have no XLA cross-process collectives, so the
    # reduction rides the elastic membership server (MXT_ELASTIC=1):
    # rank 0 hosts it at the coordinator-derived port, every worker
    # registers + heartbeats, and push rendezvouses the sum there —
    # the same code path production uses for degradable sync
    os.environ["MXT_ELASTIC"] = "1"
    mx.parallel.init_distributed()
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == n, (kv.num_workers, n)
    assert kv.rank == rank, (kv.rank, rank)
    assert kv._member is not None, "elastic membership did not engage"
    kv.init("e", nd.zeros((2, 2)))
    kv.push("e", nd.full((2, 2), rank + 1.0))
    out = nd.zeros((2, 2))
    kv.pull("e", out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               sum(r + 1.0 for r in range(n)))
    print("ENV_PASS rank=%d/%d" % (rank, n), flush=True)
    # drain: the rank-0 process hosts the server thread — peers leave
    # first (graceful deregister) so no reply is torn mid-send
    kv._barrier("env_check_done")
    if rank != 0:
        kv._member.stop(deregister=True)
    else:
        import time

        deadline = time.monotonic() + 30.0
        while set(kv._member.members()["members"]) != {0}:
            assert time.monotonic() < deadline, "peers never drained"
            time.sleep(0.02)


if __name__ == "__main__":
    main()
