"""Worker script for the real-process elastic soak (slow tier): run via

    python tools/launch.py -n 3 --launcher local --respawn \
        python tests/dist/elastic_worker.py

Worker 2's FIRST incarnation SIGKILLs itself mid-epoch; the launcher
respawns it with its original rank/env, and the second incarnation
re-registers (rejoin), receives the snapshot handoff, and pushes again.
Worker 0 hosts the membership server thread and asserts the full
sequence: death observed within the liveness window → rejoin observed →
final store state reflects the rejoined push. File markers under
ELASTIC_TEST_DIR coordinate incarnations (the launcher gives a respawn
the SAME env, which is the point).

Uses the membership/async server directly (no jax.distributed) so a
SIGKILL + respawn does not have to renegotiate the JAX coordination
service — exactly the standalone-server topology kvstore_server hosts.
"""
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from mxnet_tpu import async_server  # noqa: E402
from mxnet_tpu.membership import WorkerMembership  # noqa: E402

DEADLINE = 60.0


def _wait(cond, msg):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < DEADLINE, "timeout: " + msg
        time.sleep(0.02)


def main():
    rank = int(os.environ["MXT_WORKER_ID"])
    n = int(os.environ["MXT_NUM_WORKERS"])
    mdir = os.environ["ELASTIC_TEST_DIR"]
    host, port = async_server.server_address()
    if rank == 0:
        async_server.get_server(host, port)  # server thread lives here

    marker = os.path.join(mdir, "spawned_%d" % rank)
    first = not os.path.exists(marker)
    with open(marker, "a") as f:
        f.write("x")

    m = WorkerMembership(host, port, rank)
    m.register(want_snapshot=not first)
    m.start_heartbeats()
    cli = async_server.AsyncClient(host, port)
    cli.set_credentials(rank, m.generation)

    if rank == 0:
        cli.request("init", "w", np.zeros((4,), np.float32))
    _wait(lambda: _has_key(cli), "key init")
    cli.request("push", "w", np.full((4,), rank + 1.0, np.float32))

    if rank == 2 and first:
        # die mid-epoch, hard — the launcher must respawn us with the
        # SAME rank/env so the second incarnation rejoins
        os.kill(os.getpid(), signal.SIGKILL)

    if rank == 2 and not first:
        # rejoin handoff: the server knew this worker_id → snapshot
        assert m.snapshot is not None and "w" in m.snapshot["weights"], \
            "rejoin snapshot missing"
        cli.request("push", "w", np.full((4,), 42.0, np.float32))
        with open(os.path.join(mdir, "rejoined"), "w") as f:
            f.write("ok")

    if rank == 0:
        # death within the liveness window, then the rejoin, then the
        # rejoined incarnation's push landed
        _wait(lambda: 2 in m.members()["dead"]
              or os.path.exists(os.path.join(mdir, "rejoined")),
              "worker 2 declared dead")
        _wait(lambda: os.path.exists(os.path.join(mdir, "rejoined")),
              "worker 2 rejoin")
        _wait(lambda: cli.request("pull", "w")[0] == 42.0,
              "rejoined push visible")
        # survivors kept pushing throughout
        cli.request("push", "w", np.full((4,), 7.0, np.float32))
    if rank == 1:
        _wait(lambda: os.path.exists(os.path.join(mdir, "rejoined")),
              "rejoin before worker 1 exits")

    print("ELASTIC_PASS rank=%d/%d first=%s" % (rank, n, first),
          flush=True)
    m.stop(deregister=True)
    cli.close()
    if rank == 0:
        # worker 0 hosts the server: stay up until every peer reported
        _wait(lambda: os.path.exists(os.path.join(mdir, "rejoined")),
              "final drain")


def _has_key(cli):
    try:
        cli.request("pull", "w")
        return True
    except Exception:
        return False


if __name__ == "__main__":
    main()
