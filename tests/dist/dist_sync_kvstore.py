"""Worker script for the multi-process dist_sync test (models
tests/nightly/dist_sync_kvstore.py — run via tools/launch.py, each worker
pushes distinct values and asserts every worker converges to the same
summed state).

Run: python tools/launch.py -n 2 --launcher local \
         python tests/dist/dist_sync_kvstore.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402

# the axon sitecustomize force-sets jax_platforms; config.update wins
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def main():
    mx.parallel.init_distributed()
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == int(os.environ["MXT_NUM_WORKERS"]), (nw, os.environ)

    # 1) push/pull sync: each worker pushes rank+1; all must pull the sum
    kv.init("a", nd.zeros((4, 3)))
    kv.push("a", nd.full((4, 3), rank + 1.0))
    out = nd.zeros((4, 3))
    kv.pull("a", out=out)
    expect = sum(r + 1.0 for r in range(nw))
    np.testing.assert_allclose(out.asnumpy(), expect)

    # 2) trainer-level: identical weights on every worker after a step on
    # different per-worker data
    from mxnet_tpu import autograd as ag
    mx.random.seed(7)  # same init on every worker
    net = mx.gluon.nn.Dense(2)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1}, kvstore=kv)
    rng = np.random.RandomState(100 + rank)  # different data per worker
    x = nd.array(rng.normal(size=(8, 5)).astype("f4"))
    with ag.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    trainer.step(8)
    w = net.weight.data().asnumpy()
    # gather every worker's weights; all rows must match
    from mxnet_tpu.parallel.sharded import allreduce_across_processes
    mean_w = allreduce_across_processes(nd.array(w / nw)).asnumpy()
    np.testing.assert_allclose(w, mean_w, rtol=1e-5, atol=1e-6)

    # 3) 2-bit gradient compression with error feedback across the ring
    kv2 = mx.kv.create("dist_sync")
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv2.init("c", nd.zeros((2, 2)))
    kv2.push("c", nd.full((2, 2), 0.3))  # below threshold -> all-zero push
    out = nd.zeros((2, 2))
    kv2.pull("c", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.0)
    kv2.push("c", nd.full((2, 2), 0.3))  # residual 0.6 crosses 0.5
    kv2.pull("c", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5 * nw)

    # 4) row_sparse over the wire (models the reference nightly's sparse
    # section, ref: kvstore_dist — PullRowSparseImpl): each worker pushes
    # different rows; the reduced store must hold the union, and
    # row_sparse_pull must return any requested row subset of it.
    from mxnet_tpu import sparse
    shape = (nw + 2, 3)
    kv3 = mx.kv.create("dist_sync")
    kv3.init("rs", nd.zeros(shape))
    rows = np.array([rank, rank + 2], np.int64)  # overlaps neighbors
    vals = np.full((2, 3), rank + 1.0, "f4")
    kv3.push("rs", sparse.row_sparse_array((vals, rows), shape=shape))
    expect = np.zeros(shape, "f4")
    for r in range(nw):
        expect[[r, r + 2]] += r + 1.0
    dense_out = nd.zeros(shape)
    kv3.pull("rs", out=dense_out)
    np.testing.assert_allclose(dense_out.asnumpy(), expect, rtol=1e-6)
    # union of every worker's touched rows
    union = np.unique(np.concatenate(
        [np.array([r, r + 2]) for r in range(nw)]))
    rs_out = sparse.zeros("row_sparse", shape)
    kv3.row_sparse_pull("rs", out=rs_out, row_ids=nd.array(
        union.astype("f4")))
    np.testing.assert_array_equal(rs_out.indices.asnumpy(), union)
    np.testing.assert_allclose(rs_out.data.asnumpy(), expect[union],
                               rtol=1e-6)
    # a single worker's own-row view pulls just those rows
    rs_own = sparse.zeros("row_sparse", shape)
    kv3.row_sparse_pull("rs", out=rs_own, row_ids=nd.array(
        rows.astype("f4")))
    np.testing.assert_allclose(rs_own.data.asnumpy(), expect[rows],
                               rtol=1e-6)

    print("DIST_PASS rank=%d/%d" % (rank, nw), flush=True)


if __name__ == "__main__":
    main()
