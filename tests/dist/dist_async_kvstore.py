"""Worker script for the multi-process dist_async test (models the async
section of tests/nightly/dist_async_kvstore.py): every worker pushes its
own gradients with NO barrier; the rank-0 parameter-server thread applies
each push on arrival (hogwild), and after an explicit cross-worker sync
every worker's pull observes ALL updates.

Run: python tools/launch.py -n 2 --launcher local \
         python tests/dist/dist_async_kvstore.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def _barrier(kv):
    """Cross-process rendezvous (the test needs a 'everyone pushed'
    point; REAL training would not barrier — that is the async point).
    Rides the MEMBERSHIP barrier over the server transport: the jax
    collective barrier needs a TPU/GPU backend, membership rides TCP and
    additionally excludes dead peers."""
    kv._barrier("dist_async_test")


def main():
    mx.parallel.init_distributed()
    kv = mx.kv.create("dist_async")
    rank, nw = kv.rank, kv.num_workers
    assert kv._async is not None, "async server did not engage"

    # 1) server-side SGD: each worker pushes (rank+1) gradients of ones;
    # with lr=1 the weight ends at -(total pushes) exactly (each push is
    # applied once, acked before the next — per-worker total order)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    kv.init("w", nd.zeros((3, 2)))
    for _ in range(rank + 1):
        kv.push("w", nd.ones((3, 2)))
    _barrier(kv)  # test-only: wait until every worker's pushes are acked
    out = nd.zeros((3, 2))
    kv.pull("w", out=out)
    total = sum(r + 1 for r in range(nw))
    np.testing.assert_allclose(out.asnumpy(), -float(total), rtol=1e-6)

    # 2) NO-barrier staleness: a worker's pull immediately after its own
    # push must already reflect that push (server applies on arrival)
    kv.init("v", nd.zeros((2,)))
    kv.push("v", nd.ones((2,)) * (rank + 1))
    mine = nd.zeros((2,))
    kv.pull("v", out=mine)
    assert float(mine.asnumpy()[0]) <= -(rank + 1) + 1e-6  # mine applied
    _barrier(kv)

    # 3) accumulate mode (no optimizer on this key's server... same
    # server; push after set_optimizer applies SGD — verify pulls agree
    # across workers after the barrier
    final = nd.zeros((2,))
    kv.pull("v", out=final)
    exp = -float(sum(r + 1 for r in range(nw)))
    np.testing.assert_allclose(final.asnumpy(), exp, rtol=1e-6)

    # 4) optimizer states live on the SERVER; save fetches them there
    if rank == 0:
        import tempfile

        f = tempfile.NamedTemporaryFile(delete=False)
        kv.save_optimizer_states(f.name, dump_optimizer=True)
        assert os.path.getsize(f.name) > 0
        kv.load_optimizer_states(f.name)
        os.unlink(f.name)
    _barrier(kv)

    # 5) store re-creation: no EADDRINUSE, fresh state after reset
    kv2 = mx.kv.create("dist_async")  # creation itself rendezvouses:
    # non-zero ranks wait for rank 0's reset (server 'world' poll) and
    # membership re-forms before create returns — no barrier needed
    kv2.init("z", nd.ones((2,)))
    out2 = nd.zeros((2,))
    kv2.pull("z", out=out2)
    np.testing.assert_allclose(out2.asnumpy(), 1.0)
    # everyone observes the init value BEFORE anyone pushes: async mode
    # makes no cross-worker ordering promise, so without this barrier a
    # fast peer's push can land before a slow worker's first pull
    _barrier(kv2)
    # no optimizer on the fresh generation: push REPLACES (CopyFromTo)
    kv2.push("z", nd.full((2,), 5.0 + rank))
    _barrier(kv2)
    kv2.pull("z", out=out2)
    assert out2.asnumpy()[0] in [5.0 + r for r in range(nw)]
    # first push to an uninitialized key initializes it
    kv2.push("fresh%d" % rank, nd.full((2,), 2.0))
    got = nd.zeros((2,))
    kv2.pull("fresh%d" % rank, out=got)
    np.testing.assert_allclose(got.asnumpy(), 2.0)

    # 6) the canonical Trainer loop over the async store: each worker
    # trains at its own pace (update_on_kvstore: push grad, server
    # applies, pull weight back) — the reference's async training shape
    _barrier(kv2)  # everyone done with kv2 before its world is reset
    kv3 = mx.kv.create("dist_async")
    from mxnet_tpu import autograd as ag
    from mxnet_tpu import gluon

    mx.random.seed(11)  # same init everywhere; server keeps rank 0's
    net = gluon.nn.Dense(2, in_units=3, prefix="anet_")
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=kv3)
    rng = np.random.RandomState(300 + rank)
    for _ in range(3 + rank):  # deliberately different step counts
        x = nd.array(rng.normal(size=(4, 3)).astype("f4"))
        with ag.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(4)
    assert trainer._update_on_kvstore is True
    _barrier(kv3)  # all pushes acked server-side
    # sharp check: the SERVER optimizer's update counter proves every
    # worker's every push was applied exactly once (weight-value checks
    # alone are tautological — all ranks pull the same server state)
    if rank == 0:
        import pickle as _pickle

        blob = kv3._async.request("get_states", None, True)
        states, server_opt = _pickle.loads(blob)
        total_steps = sum(3 + r for r in range(nw))
        counts = dict(server_opt._index_update_count)
        assert counts, "server optimizer never updated"
        # every param key saw exactly total_steps updates
        for k, c in counts.items():
            assert c == total_steps, (k, c, total_steps)
        assert len(states) > 0
    # and the weight genuinely moved off its init
    w_final = nd.zeros(net.weight.data().shape)
    kv3.pull(0, out=w_final)
    mx.random.seed(11)
    ref_net = gluon.nn.Dense(2, in_units=3, prefix="refnet_")
    ref_net.initialize()
    assert not np.allclose(w_final.asnumpy(),
                           ref_net.weight.data().asnumpy())

    print("ASYNC_PASS rank=%d/%d" % (rank, nw), flush=True)


if __name__ == "__main__":
    main()
