"""Model zoo forward-shape tests (models tests/python/unittest/test_gluon_model_zoo.py).

The reference test instantiates every zoo model and runs a forward pass on a
synthetic batch; heavy 224x224 models use a small batch to keep CPU CI fast.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import model_zoo

# smaller, fast-compiling representatives run in CI; full-size variants are
# construction-checked only (parameter shapes resolved, no forward)
FORWARD_MODELS = [
    ("resnet18_v1", (1, 3, 224, 224)),
    ("resnet18_v2", (1, 3, 224, 224)),
    ("mobilenet0.25", (1, 3, 224, 224)),
    ("mobilenetv2_0.25", (1, 3, 224, 224)),
    ("squeezenet1.1", (1, 3, 224, 224)),
]
CONSTRUCT_MODELS = [
    "resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
    "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
    "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg16_bn",
    "alexnet", "densenet121", "densenet169", "densenet201",
    "squeezenet1.0", "mobilenet1.0", "mobilenet0.5", "mobilenetv2_1.0",
    "inceptionv3",
]


@pytest.mark.parametrize("name,shape", FORWARD_MODELS)
def test_model_forward(name, shape):
    net = model_zoo.get_model(name, classes=10)
    net.initialize()
    x = nd.array(np.random.uniform(size=shape).astype(np.float32))
    out = net(x)
    assert out.shape == (shape[0], 10)
    assert np.all(np.isfinite(out.asnumpy()))


@pytest.mark.parametrize("name", CONSTRUCT_MODELS)
def test_model_constructs(name):
    net = model_zoo.get_model(name, classes=10)
    assert net is not None


def test_get_model_errors():
    with pytest.raises(ValueError):
        model_zoo.get_model("not_a_model")
    with pytest.raises(ValueError):
        model_zoo.get_model("resnet18_v1", pretrained=True)


def test_resnet50_train_step():
    """One training step on resnet50 (bottleneck path + BN stats update)."""
    net = model_zoo.get_model("resnet50_v1", classes=10)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.01})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.uniform(size=(2, 3, 32, 32)).astype(np.float32))
    y = nd.array(np.array([1, 2], dtype=np.float32))
    with mx.autograd.record():
        out = net(x)
        loss = loss_fn(out, y).mean()
    loss.backward()
    trainer.step(2)
    assert np.isfinite(float(loss.asscalar()))
