"""Model zoo forward-shape tests (models tests/python/unittest/test_gluon_model_zoo.py).

The reference test instantiates every zoo model and runs a forward pass on a
synthetic batch; heavy 224x224 models use a small batch to keep CPU CI fast.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import model_zoo

# smaller, fast-compiling representatives run in CI; full-size variants are
# construction-checked only (parameter shapes resolved, no forward)
FORWARD_MODELS = [
    ("resnet18_v1", (1, 3, 224, 224)),
    ("resnet18_v2", (1, 3, 224, 224)),
    ("mobilenet0.25", (1, 3, 224, 224)),
    ("mobilenetv2_0.25", (1, 3, 224, 224)),
    ("squeezenet1.1", (1, 3, 224, 224)),
]
CONSTRUCT_MODELS = [
    "resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
    "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
    "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg16_bn",
    "alexnet", "densenet121", "densenet169", "densenet201",
    "squeezenet1.0", "mobilenet1.0", "mobilenet0.5", "mobilenetv2_1.0",
    "inceptionv3",
]


@pytest.mark.parametrize("name,shape", FORWARD_MODELS)
def test_model_forward(name, shape):
    net = model_zoo.get_model(name, classes=10)
    net.initialize()
    x = nd.array(np.random.uniform(size=shape).astype(np.float32))
    out = net(x)
    assert out.shape == (shape[0], 10)
    assert np.all(np.isfinite(out.asnumpy()))


@pytest.mark.parametrize("name", CONSTRUCT_MODELS)
def test_model_constructs(name):
    net = model_zoo.get_model(name, classes=10)
    assert net is not None


def test_get_model_errors():
    with pytest.raises(ValueError):
        model_zoo.get_model("not_a_model")
    with pytest.raises(ValueError):
        model_zoo.get_model("resnet18_v1", pretrained=True)


def test_resnet50_train_step():
    """One training step on resnet50 (bottleneck path + BN stats update)."""
    net = model_zoo.get_model("resnet50_v1", classes=10)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.01})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.uniform(size=(2, 3, 32, 32)).astype(np.float32))
    y = nd.array(np.array([1, 2], dtype=np.float32))
    with mx.autograd.record():
        out = net(x)
        loss = loss_fn(out, y).mean()
    loss.backward()
    trainer.step(2)
    assert np.isfinite(float(loss.asscalar()))


def test_gpt_causal_lm_trains_and_ties_head():
    """GPT zoo model: causality holds, the head is tied to the token
    embedding, and a few Adam steps reduce the LM loss."""
    from mxnet_tpu.gluon import model_zoo

    mx.random.seed(0)
    net = model_zoo.gpt_mini(dropout=0.0)
    net.initialize()

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 1000, (2, 24)).astype("f4"))
    out = net(x)
    assert out.shape == (2, 24, 1000)

    # tied head: exactly one (1000, 128) weight shared by embed + head
    params = net.collect_params()
    vocab_weights = [k for k, p in params.items()
                     if p.shape == (1000, 128)]
    assert len(vocab_weights) == 1, vocab_weights

    # causality: changing a future token must not affect earlier logits
    x2 = x.asnumpy().copy()
    x2[:, 20] = (x2[:, 20] + 7) % 1000
    out2 = net(nd.array(x2))
    np.testing.assert_allclose(out.asnumpy()[:, :20],
                               out2.asnumpy()[:, :20], rtol=1e-4,
                               atol=1e-4)
    assert np.abs(out.asnumpy()[:, 20:] - out2.asnumpy()[:, 20:]).max() > 1e-3

    # trains
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(params, "adam", {"learning_rate": 3e-3})
    y = nd.array(np.roll(x.asnumpy(), -1, axis=1))
    losses = []
    for _ in range(8):
        with mx.autograd.record():
            o = net(x)
            loss = loss_fn(o.reshape((-1, 1000)), y.reshape((-1,))).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.8, losses


def test_gpt_sharded_tensor_parallel_step():
    """gpt.tensor_parallel_rules on a dp2 x tp4 mesh must reproduce the
    pure-dp loss and parameter updates (a wrong spec would still be
    finite — numeric agreement is the real check)."""
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import model_zoo

    def build():
        mx.random.seed(4)
        net = model_zoo.gpt_mini(dropout=0.0)
        net.initialize()
        return net

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 1000, (8, 16)).astype("f4"))
    y = nd.array(rng.randint(0, 1000, (8, 16)).astype("f4"))

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    class SeqLoss:
        def __call__(self, out, label):
            return loss_fn(out.reshape((-1, out.shape[-1])),
                           label.reshape((-1,)))

    net_dp = build()
    net_dp(x)
    step_dp = parallel.ShardedTrainStep(
        net_dp, SeqLoss(), "adam", {"learning_rate": 1e-3},
        mesh=parallel.make_mesh(axis_names=("data",)))
    loss_a = step_dp(x, y)

    net_tp = build()
    net_tp(x)
    step_tp = parallel.ShardedTrainStep(
        net_tp, SeqLoss(), "adam", {"learning_rate": 1e-3},
        mesh=parallel.make_mesh((2, 4), ("data", "model")),
        rules=model_zoo.gpt.tensor_parallel_rules())
    loss_b = step_tp(x, y)

    assert abs(float(loss_a.asscalar()) - float(loss_b.asscalar())) < 1e-4
    pa = dict(net_dp.collect_params().items())
    pb = dict(net_tp.collect_params().items())
    for (ka, va), (kb, vb) in zip(sorted(pa.items()), sorted(pb.items())):
        np.testing.assert_allclose(va.data().asnumpy(),
                                   vb.data().asnumpy(),
                                   rtol=2e-3, atol=2e-4, err_msg=ka)
