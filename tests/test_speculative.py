"""Speculative + quantized decode (mxnet_tpu/serving/speculative.py,
quantized PagedKVCache pools, weight-only int8 matmuls routed by
tuning.resolve_quant).

The PR-12 acceptance surface on CPU:

- greedy token-EXACTNESS of the speculative engine vs the plain engine
  across mixed ragged traffic (bit-identical streams — speculation may
  change the schedule, never the output), including k > remaining
  budget and EOS-landing-inside-a-draft-window edge cases;
- quantized-KV capacity: an int8 pool holding the SAME device byte
  budget seats >= 1.9x the pages/resident sequences, at bounded output
  divergence (and exact parity against the quantized oracle);
- the async contract survives speculation: <= 1 host sync per K decode
  rounds, accept rows riding the in-flight window;
- resolve_quant table semantics (pow2 buckets, measured-wins);
- chaos: a speculative fleet's replica_kill failover replays in-flight
  requests token-exact (no re-decode divergence) — swept per seed by
  tools/chaos_matrix.sh.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from mxnet_tpu import engine as eng_mod
from mxnet_tpu import nd, profiler, serving, telemetry, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ops import quantization as Q
from mxnet_tpu.serving import (ContinuousBatcher, DecodeEngine,
                               PagedKVCache, Request, SpeculativeEngine,
                               TinyDecoder)


@pytest.fixture(autouse=True)
def _fresh_table(monkeypatch, tmp_path):
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    yield
    tuning.reset()


MODEL = TinyDecoder(vocab=128, num_layers=2, num_heads=2, head_dim=16,
                    max_len=512)
PARAMS = MODEL.init_params(3)
DRAFT, DPARAMS = MODEL.truncated(PARAMS, 1)

_ENGINES = {}  # (spec, quantized, k) -> engine, reused when drained


def _engine(spec, quantized=False, k=4, fresh=False):
    key = (spec, quantized, k)
    if not fresh and key in _ENGINES:
        eng = _ENGINES[key]
        if eng.cache.pages_in_use() == 0 and not eng._seq_of_slot:
            return eng
    if spec:
        eng = SpeculativeEngine(
            MODEL, DRAFT, params=PARAMS, draft_params=DPARAMS,
            draft_k=k, slots=4,
            cache=PagedKVCache(2, 2, 16, num_pages=128, page_size=8,
                               quantized=quantized),
            draft_cache=PagedKVCache(1, 2, 16, num_pages=128,
                                     page_size=8, quantized=quantized),
            prefill_buckets=(16,), max_context=128)
    else:
        eng = DecodeEngine(
            MODEL, params=PARAMS, slots=4,
            cache=PagedKVCache(2, 2, 16, num_pages=128, page_size=8,
                               quantized=quantized),
            prefill_buckets=(16,), max_context=128)
    if not fresh:
        _ENGINES[key] = eng
    return eng


def _traffic():
    rng = np.random.RandomState(0)
    return [(rng.randint(1, 128, plen).tolist(), mnew)
            for plen, mnew in [(3, 6), (9, 4), (1, 8), (14, 3), (5, 12),
                               (2, 7), (30, 1), (8, 2)]]


def _run(eng, traffic):
    sched = ContinuousBatcher(eng)
    reqs = [sched.submit(Request(p, max_new_tokens=m))
            for p, m in traffic]
    sched.run(max_steps=20000)
    return reqs, sched


# ---------------------------------------------------------------------------
# greedy token-exactness
# ---------------------------------------------------------------------------
def test_speculative_matches_plain_engine_mixed_traffic():
    """8 mixed-ragged requests through 4 slots: the speculative stream
    is BIT-identical to the plain engine's, which is itself the
    cache-free dense oracle's."""
    base, bs = _run(_engine(False), _traffic())
    spec, ss = _run(_engine(True), _traffic())
    for a, b in zip(base, spec):
        assert a.state == b.state == "completed"
        assert a.output_tokens == b.output_tokens
    # fewer scheduler rounds: that is the whole point
    assert ss.steps < bs.steps
    ref = MODEL.reference_decode(PARAMS, base[0].prompt,
                                 base[0].max_new_tokens)
    assert base[0].output_tokens == ref


def test_speculative_k_exceeds_remaining_budget():
    """max_new < draft_k: the verify window overshoots the budget, the
    scheduler discards the tail, the stream is still exact (and the
    overshoot pages were covered by the admission slack)."""
    for p, m in [([7, 3], 1), ([5], 2), ([9, 1, 4], 3)]:
        spec, _ = _run(_engine(True), [(p, m)])
        assert spec[0].state == "completed"
        assert spec[0].output_tokens == MODEL.reference_decode(
            PARAMS, p, m)
        assert len(spec[0].output_tokens) == m


def test_speculative_eos_inside_draft_window():
    """EOS produced mid-draft-window: generation stops AT the first
    EOS exactly (post-EOS tokens of the same verify row discarded)."""
    prompt = [5, 9, 2]
    ref = MODEL.reference_decode(PARAMS, prompt, 10)
    eos = ref[2]
    stop = ref.index(eos) + 1
    sched = ContinuousBatcher(_engine(True))
    r = sched.submit(Request(prompt, max_new_tokens=10, eos_id=eos))
    sched.run()
    assert r.state == "completed"
    assert r.output_tokens == ref[:stop]
    assert r.output_tokens[-1] == eos


def test_speculative_draft_k_validation():
    with pytest.raises(MXNetError):
        SpeculativeEngine(MODEL, DRAFT, params=PARAMS,
                          draft_params=DPARAMS, draft_k=1, slots=2)


# ---------------------------------------------------------------------------
# the async contract with speculation on
# ---------------------------------------------------------------------------
def test_spec_decode_loop_sync_bound():
    """<= 1 host sync per K rounds once steady — the staged (B, k+1)
    accept rows retire through ONE deferred read like plain tokens."""
    eng = _engine(True)
    sched = ContinuousBatcher(eng)
    sched.submit(Request([5, 9, 2], max_new_tokens=60))
    for _ in range(3):
        sched.step()
    with eng_mod.bulk(4):
        h0 = profiler.host_sync_count()
        for _ in range(8):
            sched.step()
        syncs = profiler.host_sync_count() - h0
    assert syncs <= 8 // 4 + 1, \
        "spec decode loop performed %d syncs over 8 rounds at K=4" % syncs
    sched.run()
    nd.waitall()


def test_spec_acceptance_metrics():
    def total(name):
        fam = telemetry.registry().get(name)
        return sum(ch.value for ch in fam.children().values()) \
            if fam else 0.0

    p0 = total("mxt_serving_spec_proposed_tokens_total")
    a0 = total("mxt_serving_spec_accepted_tokens_total")
    reqs, _ = _run(_engine(True), _traffic())
    proposed = total("mxt_serving_spec_proposed_tokens_total") - p0
    accepted = total("mxt_serving_spec_accepted_tokens_total") - a0
    assert proposed > 0
    assert 0 <= accepted <= proposed


# ---------------------------------------------------------------------------
# quantized KV pages
# ---------------------------------------------------------------------------
def test_kv_quant_double_resident_capacity():
    """Same device byte budget -> >= 1.9x pages AND >= 1.9x concurrent
    resident sequences through a slot-rich engine."""
    budget = 512 << 10
    pf = PagedKVCache.pages_for_budget(budget, 2, 2, 16, page_size=8,
                                       quantized=False)
    pq = PagedKVCache.pages_for_budget(budget, 2, 2, 16, page_size=8,
                                       quantized=True)
    assert pq >= 1.9 * pf
    # live capacity: sequences of 4 pages each until reservation fails
    def resident(quantized, pages):
        cache = PagedKVCache(2, 2, 16, num_pages=pages, page_size=8,
                             quantized=quantized)
        n = 0
        while cache.reserve("s%d" % n, 32):
            n += 1
        return n

    rf = resident(False, pf)
    rq = resident(True, pq)
    assert rq >= 1.9 * rf
    # the byte accounting is real: both pools fit the budget
    cf = PagedKVCache(2, 2, 16, num_pages=pf, page_size=8)
    cq = PagedKVCache(2, 2, 16, num_pages=pq, page_size=8,
                      quantized=True)
    assert sum(a.nbytes for a in cf.state()) <= budget
    assert sum(a.nbytes for a in cq.state()) <= budget


def test_kv_quant_bounded_divergence_and_internal_exactness():
    """int8 pages: output streams stay CLOSE to the f32 engine's
    (bounded divergence), and the quantized engine is internally exact
    (speculative == plain under the same quantized pools)."""
    base, _ = _run(_engine(False), _traffic())
    q8, _ = _run(_engine(False, quantized=True), _traffic())
    total = sum(len(r.output_tokens) for r in base)
    same = sum(sum(1 for x, y in zip(a.output_tokens, b.output_tokens)
                   if x == y) for a, b in zip(base, q8))
    assert same / total >= 0.8, \
        "int8 KV diverged on %d/%d tokens" % (total - same, total)
    spec_q, _ = _run(_engine(True, quantized=True), _traffic())
    for a, b in zip(q8, spec_q):
        assert a.output_tokens == b.output_tokens


def test_kv_quant_attention_parity():
    """The quantized gather fallback: dequantized paged attention is
    close to the f32 path on the same logical values."""
    rng = np.random.RandomState(2)
    B, H, D, S, P = 2, 2, 16, 8, 10
    q = jnp.asarray(rng.normal(size=(B, H, D)).astype("f4"))
    k = rng.normal(size=(P, S, H, D)).astype("f4")
    v = rng.normal(size=(P, S, H, D)).astype("f4")
    pt = jnp.asarray([[0, 1, 2], [3, 4, 5]], dtype=jnp.int32)
    cl = jnp.asarray([5, 23], dtype=jnp.int32)
    ref = np.array(nd.ragged_paged_attention(
        q, jnp.asarray(k), jnp.asarray(v), pt, cl).data)

    def quant(x):
        amax = np.abs(x).max(axis=-1)
        qx = np.clip(np.round(x * (127.0 / np.maximum(amax, 1e-30))
                              [..., None]), -127, 127).astype(np.int8)
        return jnp.asarray(qx), jnp.asarray(amax.astype("f4"))

    kq, ks = quant(k)
    vq, vs = quant(v)
    got = np.array(nd.ragged_paged_attention(
        q, kq, vq, pt, cl, k_scales=ks, v_scales=vs).data)
    np.testing.assert_allclose(got, ref, atol=5e-2)


# ---------------------------------------------------------------------------
# weight-only quantization + resolve_quant
# ---------------------------------------------------------------------------
def test_woq_matmul_parity():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(size=(4, 64)).astype("f4"))
    w = jnp.asarray(rng.normal(size=(64, 96)).astype("f4"))
    qw, amax = Q.quantize_rowwise(w)
    assert qw.dtype == jnp.int8 and amax.shape == (96,)
    got = np.array(Q.woq_matmul(x, qw, amax))
    np.testing.assert_allclose(got, np.array(x @ w), atol=2e-1)
    # dequantized weight error is the int8 grid, column-scaled
    deq = np.array(qw, dtype=np.float32) * (np.array(amax) / 127.0)
    assert np.max(np.abs(deq - np.array(w))) <= np.max(np.array(amax)) \
        / 127.0 + 1e-6


def test_quantize_params_routing_and_exactness():
    """quantize_params stores int8 where resolve_quant says 'int8';
    the quantized ENGINE matches the quantized ORACLE token for token
    (quantization shifts the function, never the engine's fidelity)."""
    qparams, report = MODEL.quantize_params(PARAMS)
    assert report and all(b in ("int8", "fp") for b in report.values())
    assert any(k.endswith("__q") for k in qparams) \
        or all(b == "fp" for b in report.values())
    prompt = [5, 9, 2, 44]
    eng = DecodeEngine(MODEL, params=qparams, slots=2,
                       cache=PagedKVCache(2, 2, 16, num_pages=64,
                                          page_size=8),
                       prefill_buckets=(16,), max_context=64)
    sched = ContinuousBatcher(eng)
    r = sched.submit(Request(prompt, max_new_tokens=8))
    sched.run()
    assert r.output_tokens == MODEL.reference_decode(qparams, prompt, 8)


def test_resolve_quant_table_semantics():
    # pow2 bucketing: nearby shapes share a key, measured entries win
    k1 = tuning.quant_key("woq_matmul", 65, 190, "float32")
    k2 = tuning.quant_key("woq_matmul", 127, 255, "float32")
    assert k1 == k2
    ent = tuning.resolve_quant("woq_matmul", 64, 192, "float32")
    assert ent["backend"] in ("int8", "fp")
    assert ent["source"] == "heuristic"
    key = tuning.quant_key("woq_matmul", 64, 192, "float32")
    tuning.table().record(key, {"backend": "fp", "source": "measured"})
    assert tuning.resolve_quant(
        "woq_matmul", 64, 192, "float32")["backend"] == "fp"
    # heuristic re-record never downgrades the measured entry
    tuning.table().record(key, {"backend": "int8",
                                "source": "heuristic"})
    assert tuning.table().peek(key)["source"] == "measured"
    # tiny layers stay fp, big decode matmuls go int8
    assert tuning.heuristic_quant("woq_matmul", 8, 8,
                                  "float32")["backend"] == "fp"
    assert tuning.heuristic_quant("woq_matmul", 256, 1024,
                                  "float32")["backend"] == "int8"


# ---------------------------------------------------------------------------
# AOT warm + recomposition with speculation
# ---------------------------------------------------------------------------
def test_spec_aot_warmup_and_defrag():
    eng = _engine(True, fresh=True)
    # fused round + one fused two-model admission per bucket
    assert eng.aot_warmup() >= 2
    sched = ContinuousBatcher(eng)
    a = sched.submit(Request([3, 1, 4, 1, 5], max_new_tokens=8))
    b = sched.submit(Request([9, 2], max_new_tokens=8))
    for _ in range(2):
        sched.step()
    eng.flush()
    eng.defrag()
    sched.run()
    for r in (a, b):
        assert r.output_tokens == MODEL.reference_decode(
            PARAMS, r.prompt, r.max_new_tokens)


# ---------------------------------------------------------------------------
# chaos: speculative fleet failover replays token-exact
# ---------------------------------------------------------------------------
def _spec_factory():
    return SpeculativeEngine(
        MODEL, DRAFT, params=PARAMS, draft_params=DPARAMS, draft_k=3,
        slots=2,
        cache=PagedKVCache(2, 2, 16, num_pages=64, page_size=8),
        draft_cache=PagedKVCache(1, 2, 16, num_pages=64, page_size=8),
        prefill_buckets=(16,), max_context=64)


@pytest.mark.chaos
def test_chaos_spec_fleet_replica_kill_replay(monkeypatch):
    """Seeded replica_kill on a SPECULATIVE-engine fleet: the router
    fails the dead replica's in-flight requests over and every stream
    completes token-exact vs the oracle — failover replays speculative
    requests without re-decode divergence."""
    from mxnet_tpu import resilience
    from mxnet_tpu.serving import FleetRouter

    seed = int(os.environ.get("MXT_CHAOS_SEED", "0"))
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.02")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.05")
    monkeypatch.setenv(
        "MXT_FAULT", "replica_kill:replica=1,after=2,n=1,seed=%d" % seed)
    resilience.reset_faults()
    try:
        pool, srv = serving.local_serving_fleet(2, _spec_factory)
        router = FleetRouter(pool)
        rng = np.random.RandomState(seed)
        reqs = [router.submit(rng.randint(1, 128, 4).tolist(),
                              max_new_tokens=8, token="sk%d" % i)
                for i in range(6)]
        router.run(max_steps=4000)
        assert pool.get(1).state == "dead"
        assert all(rr.state == "completed" for rr in reqs)
        for rr in reqs:
            assert rr.result == MODEL.reference_decode(
                PARAMS, rr.prompt, rr.max_new_tokens), rr.token
        assert sum(rr.failovers for rr in reqs) > 0
        for h in pool.replicas():
            try:
                h.close()
            except Exception:  # noqa: BLE001 — killed handles
                pass
        srv.close()
    finally:
        resilience.reset_faults()


# ---------------------------------------------------------------------------
# lint + telemetry surface
# ---------------------------------------------------------------------------
def test_speculative_module_lint_enforced():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert "mxnet_tpu/serving/speculative.py" in m.SCAN
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = [b for b in m.check(root)
           if b[0].startswith(("mxnet_tpu/serving/",
                               "mxnet_tpu/embedding/"))]
    assert not bad, bad


def test_mxt_top_spec_and_quant_lines():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    samples = {
        ("mxt_serving_tokens_total", frozenset()): 120,
        ("mxt_serving_spec_proposed_tokens_total", frozenset()): 90,
        ("mxt_serving_spec_accepted_tokens_total", frozenset()): 60,
        ("mxt_serving_kv_quant_pages_in_use", frozenset()): 7,
    }
    frame = mod.render(samples, None, 0)
    assert "spec accept" in frame and "0.667" in frame
    assert "int8 kv pages" in frame
    # a non-speculative f32 replica renders neither line
    plain = mod.render({("mxt_serving_tokens_total", frozenset()): 5},
                       None, 0)
    assert "spec accept" not in plain and "int8 kv pages" not in plain
