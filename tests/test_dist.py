"""Multi-process distributed tests (SURVEY §4 'distributed without a real
cluster': real kvstore code over localhost processes via the launcher,
no mocks)."""
import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("n", [2, 4])
def test_dist_sync_kvstore_local_launcher(n):
    env = dict(os.environ)
    env.pop("MXT_COORDINATOR", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local", sys.executable,
         os.path.join(ROOT, "tests", "dist", "dist_sync_kvstore.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert r.stdout.count("DIST_PASS") == n, r.stdout[-2000:]


def test_launcher_cli_errors():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "ssh", "python", "x.py"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0
    assert "hostfile" in r.stderr
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0
    assert "no command" in r.stderr


@pytest.mark.parametrize("n,secret", [
    (2, None),
    (4, None),
    # MXT_KVSTORE_SECRET set: the launcher forwards the secret to every
    # worker and frames are HMAC'd (nonce|dir|seq) — trust-boundary
    # hardening, round 5
    (4, "dist-test-secret"),
])
def test_dist_async_kvstore_hogwild(n, secret):
    """dist_async under the launcher engages the REAL parameter-server
    thread (async_server.py): pushes apply on arrival with no barrier."""
    env = dict(os.environ)
    env.pop("MXT_COORDINATOR", None)
    env.pop("MXT_KVSTORE_SECRET", None)
    if secret is not None:
        env["MXT_KVSTORE_SECRET"] = secret
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local", sys.executable,
         os.path.join(ROOT, "tests", "dist", "dist_async_kvstore.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert r.stdout.count("ASYNC_PASS") == n, r.stdout[-2000:]


def test_launch_local_env_plumbing_and_sync_reduction():
    """Satellite: launch_local's rank/coordinator/secret forwarding was
    untested — 2 subprocess workers assert the env contract and complete
    a sync reduction through the launched rendezvous."""
    env = dict(os.environ)
    env.pop("MXT_COORDINATOR", None)
    env["MXT_KVSTORE_SECRET"] = "env-plumb-secret"
    env["LAUNCH_TEST_EXPECT_SECRET"] = "env-plumb-secret"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable,
         os.path.join(ROOT, "tests", "dist", "launch_env_check.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert r.stdout.count("ENV_PASS") == 2, r.stdout[-2000:]


def test_worker_env_contract():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    env = launch._worker_env({"MXT_KVSTORE_SECRET": "s3"},
                             "127.0.0.1:9999", 4, 2)
    assert env["MXT_COORDINATOR"] == "127.0.0.1:9999"
    assert env["MXT_NUM_WORKERS"] == "4" and env["MXT_WORKER_ID"] == "2"
    assert env["DMLC_NUM_WORKER"] == "4" and env["DMLC_WORKER_ID"] == "2"
    assert env["DMLC_ROLE"] == "worker"
    assert env["MXT_KVSTORE_SECRET"] == "s3"  # base env forwarded


def test_launch_respawn_restarts_crashed_worker(tmp_path):
    """--respawn restarts a non-zero exit with the ORIGINAL rank/env:
    worker 1 crashes on its first incarnation (sentinel file) and
    succeeds on the respawn; the launch as a whole exits 0."""
    env = dict(os.environ)
    env["CRASH_MARKER"] = str(tmp_path / "spawn_")
    prog = ("import os,sys;"
            "p=os.environ['CRASH_MARKER']+os.environ['MXT_WORKER_ID'];"
            "first=not os.path.exists(p);open(p,'a').write('x');"
            "sys.exit(1 if first and os.environ['MXT_WORKER_ID']=='1' "
            "else 0)")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--respawn",
         sys.executable, "-c", prog],
        capture_output=True, text=True, timeout=120, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "respawning with original rank/env" in r.stderr
    # worker 1 ran twice (crash + respawn), worker 0 once
    assert (tmp_path / "spawn_1").read_text() == "xx"
    assert (tmp_path / "spawn_0").read_text() == "x"


def test_launch_respawn_budget_exhausted(tmp_path):
    """A worker that keeps crashing exhausts --max-restarts and the
    launch reports its failure instead of looping forever."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "1", "--launcher", "local", "--respawn",
         "--max-restarts", "1", sys.executable, "-c",
         "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 3, (r.returncode, r.stderr)
    assert r.stderr.count("respawning") == 1


def test_kvstore_server_role_serves_standalone():
    """Satellite: `python -m mxnet_tpu.kvstore_server` launched as a
    role actually serves — a client can push/pull through it (the
    membership/async server hosted standalone)."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("MXT_KVSTORE_SECRET", None)
    env["MXT_COORDINATOR"] = "127.0.0.1:%d" % port
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.kvstore_server"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=ROOT)
    try:
        line = p.stdout.readline()
        assert "KVSTORE_SERVER_READY" in line, (line, p.stderr.read()
                                                if p.poll() else "")
        import numpy as np

        from mxnet_tpu import async_server

        cli = async_server.AsyncClient("127.0.0.1", port +
                                       async_server.ASYNC_PORT_OFFSET,
                                       timeout=15.0)
        cli.request("init", "w", np.full((2,), 4.0, np.float32))
        np.testing.assert_array_equal(cli.request("pull", "w"),
                                      np.full((2,), 4.0))
        cli.close()
    finally:
        p.terminate()
        p.wait(timeout=30)


@pytest.mark.slow
@pytest.mark.chaos
def test_elastic_rejoin_real_processes(tmp_path):
    """Real-process acceptance variant (slow): 3 workers under
    --respawn, worker 2 SIGKILLs itself mid-epoch, is respawned with its
    original rank/env, rejoins via snapshot handoff, and the survivors
    observe the death within the liveness window."""
    env = dict(os.environ)
    env.pop("MXT_COORDINATOR", None)
    env["ELASTIC_TEST_DIR"] = str(tmp_path)
    env["MXT_HEARTBEAT_INTERVAL"] = "0.1"
    env["MXT_LIVENESS_TIMEOUT"] = "0.5"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "3", "--launcher", "local", "--respawn",
         sys.executable,
         os.path.join(ROOT, "tests", "dist", "elastic_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    # 4 passes: ranks 0/1 + BOTH incarnations... the killed first
    # incarnation never prints, so: rank0, rank1, rank2-respawn
    assert r.stdout.count("ELASTIC_PASS") == 3, r.stdout[-2000:]
    assert "first=False" in r.stdout  # the rejoined incarnation
    assert (tmp_path / "rejoined").exists()
    assert (tmp_path / "spawned_2").read_text() == "xx"  # ran twice
