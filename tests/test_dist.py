"""Multi-process distributed tests (SURVEY §4 'distributed without a real
cluster': real kvstore code over localhost processes via the launcher,
no mocks)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("n", [2, 4])
def test_dist_sync_kvstore_local_launcher(n):
    env = dict(os.environ)
    env.pop("MXT_COORDINATOR", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local", sys.executable,
         os.path.join(ROOT, "tests", "dist", "dist_sync_kvstore.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert r.stdout.count("DIST_PASS") == n, r.stdout[-2000:]


def test_launcher_cli_errors():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "ssh", "python", "x.py"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0
    assert "hostfile" in r.stderr
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0
    assert "no command" in r.stderr


@pytest.mark.parametrize("n,secret", [
    (2, None),
    (4, None),
    # MXT_KVSTORE_SECRET set: the launcher forwards the secret to every
    # worker and frames are HMAC'd (nonce|dir|seq) — trust-boundary
    # hardening, round 5
    (4, "dist-test-secret"),
])
def test_dist_async_kvstore_hogwild(n, secret):
    """dist_async under the launcher engages the REAL parameter-server
    thread (async_server.py): pushes apply on arrival with no barrier."""
    env = dict(os.environ)
    env.pop("MXT_COORDINATOR", None)
    env.pop("MXT_KVSTORE_SECRET", None)
    if secret is not None:
        env["MXT_KVSTORE_SECRET"] = secret
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local", sys.executable,
         os.path.join(ROOT, "tests", "dist", "dist_async_kvstore.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert r.stdout.count("ASYNC_PASS") == n, r.stdout[-2000:]
