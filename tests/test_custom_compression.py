"""CustomOp escape hatch + gradient compression tests (models
tests/python/unittest/test_operator.py::test_custom_op and the
compression coverage in tests/nightly/dist_sync_kvstore.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import autograd as ag
from mxnet_tpu.base import MXNetError


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-in_data[0])))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Sigmoid()


class _SplitHalf(mx.operator.CustomOp):
    """Two-output custom op: splits the last axis in half."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        h = x.shape[-1] // 2
        self.assign(out_data[0], req[0], x[..., :h])
        self.assign(out_data[1], req[1], x[..., h:])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    np.concatenate([out_grad[0], out_grad[1]], axis=-1))


@mx.operator.register("test_split_half")
class _SplitHalfProp(mx.operator.CustomOpProp):
    def list_outputs(self):
        return ["left", "right"]

    def infer_shape(self, in_shape):
        s = list(in_shape[0])
        half = s[:-1] + [s[-1] // 2]
        return in_shape, [half, half], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _SplitHalf()


def test_custom_op_forward_backward():
    x = nd.array(np.linspace(-3, 3, 24).astype("f4").reshape(4, 6))
    x.attach_grad()
    with ag.record():
        y = nd.Custom(x, op_type="test_sigmoid")
        loss = (y * y).sum()
    loss.backward()
    ref = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y.asnumpy(), ref, rtol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(),
                               2 * ref * ref * (1 - ref), rtol=1e-5)


def test_custom_op_under_jit():
    """pure_callback keeps the op jit-compatible (the hybridize path)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a: mx.operator.custom(a, op_type="test_sigmoid"))
    xn = np.linspace(-1, 1, 8).astype("f4")
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(xn))),
                               1 / (1 + np.exp(-xn)), rtol=1e-6)


def test_custom_op_multi_output():
    x = nd.array(np.arange(12, dtype="f4").reshape(2, 6))
    x.attach_grad()
    with ag.record():
        left, right = nd.Custom(x, op_type="test_split_half")
        loss = left.sum() + (2 * right).sum()
    loss.backward()
    np.testing.assert_array_equal(left.asnumpy(), x.asnumpy()[:, :3])
    np.testing.assert_array_equal(right.asnumpy(), x.asnumpy()[:, 3:])
    g = x.grad.asnumpy()
    np.testing.assert_array_equal(g[:, :3], 1.0)
    np.testing.assert_array_equal(g[:, 3:], 2.0)


def test_custom_op_unregistered_raises():
    with pytest.raises(MXNetError):
        nd.Custom(nd.ones((2, 2)), op_type="nope_not_registered")


# ---------------------------------------------------------------------------
# gradient compression
# ---------------------------------------------------------------------------
def test_gradient_compression_quantize_and_residual():
    from mxnet_tpu.kvstore import GradientCompression

    gc = GradientCompression(threshold=0.5)
    g = nd.array(np.array([0.7, -0.9, 0.2, -0.3], "f4"))
    q1 = gc.compress("k", g).asnumpy()
    np.testing.assert_allclose(q1, [0.5, -0.5, 0.0, 0.0])
    # error feedback: 0.2 + 0.2 + 0.2 crosses 0.5 on the third push
    small = nd.array(np.array([0.2, 0.0, 0.0, 0.0], "f4"))
    q2 = gc.compress("k2", small).asnumpy()
    q3 = gc.compress("k2", small).asnumpy()
    q4 = gc.compress("k2", small).asnumpy()
    assert q2[0] == 0.0 and q3[0] == 0.0 and q4[0] == 0.5
    # residual after emission is 0.6 - 0.5 = 0.1
    np.testing.assert_allclose(
        np.asarray(gc.residual["k2"])[0], 0.1, atol=1e-6)


def test_gradient_compression_requires_dist():
    kv = mx.kv.create("local")
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv2 = mx.kv.create("dist_sync")
    with pytest.raises(MXNetError):
        kv2.set_gradient_compression({"type": "1bit"})
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.25})
    assert kv2._compression.threshold == 0.25
