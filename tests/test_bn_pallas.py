"""Pallas fused BN backward — interpret-mode parity vs the XLA
custom-VJP formulas (ops/nn.py _bn_core_bwd). Hardware parity lives in
tests/test_tpu_smoke.py (round-2 lesson: interpret-green is not
Mosaic-green)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import bn_pallas
from mxnet_tpu.ops.nn import _bn_core

pytestmark = pytest.mark.skipif(not bn_pallas.available(),
                                reason="pallas unavailable")


def _oracle(x2d, dy2d, g):
    """Gradients through the existing custom-VJP core (channel last).
    _bn_core returns (out, mean, var); only out carries a cotangent."""
    b = jnp.zeros_like(g)
    (out, mean, var), vjp = jax.vjp(
        lambda xx, gg, bb: _bn_core(1e-5, (0,), xx, gg, bb), x2d, g, b)
    return vjp((dy2d.astype(out.dtype), jnp.zeros_like(mean),
                jnp.zeros_like(var)))


def _stats(x2d):
    x32 = x2d.astype(jnp.float32)
    mean = jnp.mean(x32, axis=0)
    var = jnp.mean(jnp.square(x32 - mean), axis=0)
    inv = jax.lax.rsqrt(var + 1e-5)
    return mean, inv


@pytest.mark.parametrize("m,c,dtype", [
    (64, 32, jnp.float32),
    (200, 16, jnp.float32),      # m not a multiple of the block rows
    (1024, 8, jnp.bfloat16),
    (96, 128, jnp.bfloat16),
])
def test_bn_bwd_pallas_matches_xla_vjp(m, c, dtype):
    key = jax.random.PRNGKey(0)
    kx, kdy, kg = jax.random.split(key, 3)
    x = jax.random.normal(kx, (m, c), dtype)
    dy = jax.random.normal(kdy, (m, c), dtype)
    g = jax.random.normal(kg, (c,), jnp.float32) + 1.5

    mean, inv = _stats(x)
    dx, dg, db = bn_pallas.bn_bwd_pallas(x, dy, mean, inv, g,
                                         interpret=True)
    odx, odg, odb = _oracle(x, dy, g)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(db, np.float32),
                               np.asarray(odb, np.float32),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(dg, np.float32),
                               np.asarray(odg, np.float32),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(dx, np.float32),
                               np.asarray(odx, np.float32),
                               rtol=tol, atol=tol)
    assert dx.dtype == x.dtype


def test_bn_bwd_pallas_masking_exactness():
    """The remainder block's padding must not leak into the reductions:
    compare a padded-size run against a multiple-size run on the same
    data."""
    m, c = 72, 8  # 72 % block_rows != 0 for any pow2 block > 8
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (m, c), jnp.float32)
    dy = jnp.ones((m, c), jnp.float32)
    mean, inv = _stats(x)
    _, dg, db = bn_pallas.bn_bwd_pallas(x, dy, mean, inv,
                                        jnp.ones(c), interpret=True)
    np.testing.assert_allclose(np.asarray(db), np.full(c, float(m)),
                               rtol=1e-6)


def test_enabled_gating(monkeypatch):
    monkeypatch.delenv("MXT_BN_PALLAS", raising=False)
    assert not bn_pallas.enabled()  # default off
    monkeypatch.setenv("MXT_BN_PALLAS", "1")
    if jax.default_backend() in ("tpu", "axon"):
        assert bn_pallas.enabled()
    else:
        # on a CPU/GPU backend the compiled Mosaic path must stay off
        assert not bn_pallas.enabled()
