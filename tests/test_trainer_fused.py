"""Fused (one-launch, donated) Trainer.step vs the eager per-param path.

The canonical Gluon loop (ref: gluon/trainer.py — step) must produce
identical numerics whether Trainer.step runs the fused donated XLA program
or the eager per-parameter updates; these tests pin that equivalence and
the eligibility/fallback edges.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import nd
from mxnet_tpu import profiler
from mxnet_tpu.gluon import CachedTrainStep, Trainer, nn, train_step
from mxnet_tpu.gluon.trainer import _FusedUpdate


def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="fused_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    return net


def _train(net, trainer, steps=4, seed=0):
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        x = nd.array(rng.uniform(-1, 1, (8, 8)).astype(np.float32))
        y = nd.array(rng.uniform(-1, 1, (8, 4)).astype(np.float32))
        with ag.record():
            out = net(x)
            loss = ((out - y) ** 2).mean()
        loss.backward()
        trainer.step(8)
        losses.append(float(loss.asnumpy()))
    return losses


def _weights(net):
    return {k: v.data().asnumpy() for k, v in net.collect_params().items()}


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
    ("adamw", {"learning_rate": 1e-2, "wd": 1e-2}),
    ("rmsprop", {"learning_rate": 1e-3}),
    ("rmsprop", {"learning_rate": 1e-3, "centered": True}),
    ("adagrad", {"learning_rate": 0.05, "wd": 1e-4}),
])
def test_fused_matches_eager(monkeypatch, optimizer, opt_params):
    net_f = _make_net()
    tr_f = Trainer(net_f.collect_params(), optimizer, dict(opt_params))
    _train(net_f, tr_f)
    assert tr_f._fused, "fused path should be eligible here"

    monkeypatch.setenv("MXT_FUSED_TRAINER", "0")
    net_e = _make_net()
    tr_e = Trainer(net_e.collect_params(), optimizer, dict(opt_params))
    _train(net_e, tr_e)
    assert tr_e._fused is False

    wf, we = _weights(net_f), _weights(net_e)
    assert wf.keys() == we.keys()
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)
    # optimizer step counters advanced identically
    assert tr_f._optimizer.num_update == tr_e._optimizer.num_update == 4


def test_fused_with_lr_scheduler(monkeypatch):
    from mxnet_tpu.lr_scheduler import FactorScheduler

    def run(env):
        if env is not None:
            monkeypatch.setenv("MXT_FUSED_TRAINER", env)
        net = _make_net()
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.5, "momentum": 0.9,
                      "lr_scheduler": FactorScheduler(step=2, factor=0.5)})
        _train(net, tr, steps=5)
        return _weights(net), tr

    wf, tr_f = run(None)
    assert tr_f._fused
    we, _ = run("0")
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-5, atol=1e-6)


def test_fused_lr_mult(monkeypatch):
    def run(env):
        if env is not None:
            monkeypatch.setenv("MXT_FUSED_TRAINER", env)
        net = _make_net()
        for name, p in net.collect_params().items():
            if name.endswith("bias"):
                p.lr_mult = 0.0  # frozen biases exercise the static fold
        tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.2})
        _train(net, tr)
        return _weights(net)

    wf = run(None)
    we = run("0")
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-5, atol=1e-6)
    # the frozen biases really didn't move
    net0 = _make_net()
    w0 = _weights(net0)
    for k in wf:
        if k.endswith("bias"):
            np.testing.assert_array_equal(wf[k], w0[k])


def test_fused_save_load_states_roundtrip(tmp_path):
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    _train(net, tr, steps=3)
    assert tr._fused
    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)

    net2 = _make_net()
    tr2 = Trainer(net2.collect_params(), "adam", {"learning_rate": 1e-2})
    _train(net2, tr2, steps=1)  # materialize states
    tr2.load_states(fname)
    # the fused program closed over the pre-load optimizer — must rebuild
    assert tr2._fused is None
    # update counts resumed from the checkpoint, not the stale object
    assert tr2._optimizer.num_update == tr._optimizer.num_update == 3
    for i, s in tr._updaters[0].states.items():
        s2 = tr2._updaters[0].states[i]
        np.testing.assert_allclose(s[0].asnumpy(), s2[0].asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(s[1].asnumpy(), s2[1].asnumpy(),
                                   rtol=1e-6)
    # training continues through the fused path after a state load
    _train(net2, tr2, steps=1)


def test_fused_ineligible_falls_back():
    net = _make_net()
    # adadelta has no fused builder — must run eager and still train
    tr = Trainer(net.collect_params(), "adadelta", {"learning_rate": 1.0})
    losses = _train(net, tr)
    assert tr._fused is False
    assert np.isfinite(losses[-1])


def test_fused_no_per_step_retrace(monkeypatch):
    """Dynamic scalars (t, lr, rescale) are traced arguments, so the jit
    cache must stop growing after step 1 (step 0 compiles once; step 1
    recompiles once when the donated outputs re-enter as inputs) — a
    growing cache would mean a compile per step."""
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    _train(net, tr, steps=2)
    fused = tr._fused
    assert isinstance(fused, _FusedUpdate)
    steady = fused._jit._cache_size()
    _train(net, tr, steps=3, seed=1)
    assert fused._jit._cache_size() == steady <= 2


def test_tied_parameters_survive_donation():
    """Weight tying (params=other.params, the BERT MLM-decoder pattern)
    must register the tied Parameter in the borrowing block's
    collect_params(), so CachedOp passes it as a live input rather than
    baking it in as a constant — which dies as soon as the fused trainer
    donates the buffer."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import Block

    mx.random.seed(0)

    class Tied(Block):
        def __init__(self):
            super().__init__(prefix="tied_")
            with self.name_scope():
                self.embed = nn.Embedding(20, 8)
                self.decoder = nn.Dense(20, flatten=False, in_units=8,
                                        params=self.embed.params)

        def forward(self, x):
            return self.decoder(self.embed(x))

    net = Tied()
    net.initialize()
    # the tied weight must appear in the BORROWING block's params too
    tied_name = net.embed.weight.name
    assert net.decoder.weight is net.embed.weight  # actually tied
    assert tied_name in net.decoder.collect_params()
    assert len(net.collect_params()) == 2  # tied weight + decoder bias
    net.embed.hybridize()
    net.decoder.hybridize()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    x = nd.array(np.arange(6).reshape(2, 3).astype("f4"))
    y = nd.array(np.ones((2, 3), "f4"))
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(3):  # step 2+ would hit the deleted donated buffer
        with ag.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        tr.step(2)
    assert np.isfinite(float(loss.asnumpy()))


def test_tied_parameter_shape_mismatch_raises():
    from mxnet_tpu.gluon import Block

    mx.random.seed(0)
    with pytest.raises(mx.MXNetError, match="tied parameter"):
        class Bad(Block):
            def __init__(self):
                super().__init__(prefix="badtied_")
                with self.name_scope():
                    self.embed = nn.Embedding(20, 8)
                    # in_units=9 conflicts with the tied (20, 8) weight
                    self.decoder = nn.Dense(20, in_units=9,
                                            params=self.embed.params)

        Bad()


# ---------------------------------------------------------------------------
# CachedTrainStep — the whole canonical loop as ONE donated launch
# (gluon/train_step.py). Numerics must match record/backward/step exactly,
# including optimizer state and BatchNorm running stats; ineligible configs
# must fall back to the eager loop with identical results.
# ---------------------------------------------------------------------------
def _make_bn_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="fstep_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.BatchNorm(),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    return net


def _batches(steps=5, seed=0):
    rng = np.random.RandomState(seed)
    return [(nd.array(rng.uniform(-1, 1, (8, 8)).astype(np.float32)),
             nd.array(rng.uniform(-1, 1, (8, 4)).astype(np.float32)))
            for _ in range(steps)]


def _eager_loop(net, trainer, loss_fn, data):
    losses = []
    for x, y in data:
        with ag.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
        losses.append(loss.asnumpy())
    return losses


def _states_np(trainer):
    out = {}
    for i, s in trainer._updaters[0].states.items():
        leaves = s if isinstance(s, tuple) else (() if s is None else (s,))
        out[i] = [l.asnumpy() for l in leaves]
    return out


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_cached_train_step_matches_eager(optimizer, opt_params):
    loss_fn = mx.gluon.loss.L2Loss()
    data = _batches()

    net_f = _make_bn_net()
    tr_f = Trainer(net_f.collect_params(), optimizer, dict(opt_params))
    step = tr_f.fuse_step(net_f, loss_fn)
    losses_f = [step(x, y).asnumpy() for x, y in data]
    assert step.fused and step.fallback_reason is None

    net_e = _make_bn_net()
    tr_e = Trainer(net_e.collect_params(), optimizer, dict(opt_params))
    losses_e = _eager_loop(net_e, tr_e, loss_fn, data)

    for lf, le in zip(losses_f, losses_e):
        np.testing.assert_allclose(lf, le, rtol=1e-6, atol=1e-6)
    wf, we = _weights(net_f), _weights(net_e)
    assert wf.keys() == we.keys()
    for k in wf:  # includes BatchNorm running_mean/var aux state
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-6, atol=1e-6,
                                   err_msg=k)
    sf, se = _states_np(tr_f), _states_np(tr_e)
    assert sf.keys() == se.keys()
    for i in sf:
        for a, b in zip(sf[i], se[i]):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
    assert tr_f._optimizer.num_update == tr_e._optimizer.num_update == 5


def test_cached_train_step_single_launch_per_step():
    """Fused steady state = EXACTLY one compiled execution per training
    step (the whole point of whole-step fusion; ~3.4 ms per launch on the
    axon tunnel)."""
    loss_fn = mx.gluon.loss.L2Loss()
    net = _make_bn_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    step = tr.fuse_step(net, loss_fn)
    data = _batches(steps=5)
    step(*data[0]).wait_to_read()  # build + compile + base-key draw
    step(*data[1]).wait_to_read()
    c0 = profiler.launch_count()
    for x, y in data[2:]:
        step(x, y).wait_to_read()
    assert profiler.launch_count() - c0 == 3
    # ...and the eager loop pays strictly more per step
    net_e = _make_bn_net()
    tr_e = Trainer(net_e.collect_params(), "adam", {"learning_rate": 1e-2})
    _eager_loop(net_e, tr_e, loss_fn, data[:1])
    c1 = profiler.launch_count()
    _eager_loop(net_e, tr_e, loss_fn, data[1:2])
    assert profiler.launch_count() - c1 > 1


def test_cached_train_step_no_per_step_retrace():
    """Dynamic scalars (t, lr via scheduler, wd, rescale) are traced 0-d
    args — the jit cache must stop growing after the donated outputs
    re-enter as inputs once."""
    from mxnet_tpu.lr_scheduler import FactorScheduler

    loss_fn = mx.gluon.loss.L2Loss()
    net = _make_bn_net()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.5, "momentum": 0.9,
                  "lr_scheduler": FactorScheduler(step=2, factor=0.5)})
    step = tr.fuse_step(net, loss_fn)
    data = _batches(steps=8)
    for x, y in data:
        step(x, y)
    assert step._jit._cache_size() <= 2


def test_cached_train_step_ineligible_falls_back():
    """Unsupported optimizer: no exception, results identical to the
    hand-written eager loop."""
    loss_fn = mx.gluon.loss.L2Loss()
    data = _batches()
    net_a = _make_bn_net()
    tr_a = Trainer(net_a.collect_params(), "adadelta",
                   {"learning_rate": 1.0})
    step = train_step(net_a, loss_fn, tr_a)
    losses_a = [step(x, y).asnumpy() for x, y in data]
    assert step.fused is False
    assert "AdaDelta" in step.fallback_reason

    net_b = _make_bn_net()
    tr_b = Trainer(net_b.collect_params(), "adadelta",
                   {"learning_rate": 1.0})
    losses_b = _eager_loop(net_b, tr_b, loss_fn, data)
    for la, lb in zip(losses_a, losses_b):
        np.testing.assert_array_equal(la, lb)
    wf, we = _weights(net_a), _weights(net_b)
    for k in wf:
        np.testing.assert_array_equal(wf[k], we[k], err_msg=k)


def test_cached_train_step_flag_off(monkeypatch):
    monkeypatch.setenv("MXT_FUSED_STEP", "0")
    loss_fn = mx.gluon.loss.L2Loss()
    net = _make_bn_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    step = tr.fuse_step(net, loss_fn)
    data = _batches(steps=2)
    for x, y in data:
        step(x, y)
    assert step.fused is False
    assert step.fallback_reason == "MXT_FUSED_STEP=0"
    assert tr._optimizer.num_update == 2  # the eager loop really trained


def test_cached_train_step_return_outputs():
    loss_fn = mx.gluon.loss.L2Loss()
    net = _make_bn_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    step = tr.fuse_step(net, loss_fn, return_outputs=True)
    x, y = _batches(steps=1)[0]
    loss, out = step(x, y)
    assert loss.shape == (8,) and out.shape == (8, 4)
    # outputs are the pre-update forward: match a replayed forward on the
    # pre-step weights
    net_e = _make_bn_net()
    tr_e = Trainer(net_e.collect_params(), "adam", {"learning_rate": 1e-2})
    with ag.record():
        out_e = net_e(x)
        loss_e = loss_fn(out_e, y)
    np.testing.assert_allclose(out.asnumpy(), out_e.asnumpy(),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(loss.asnumpy(), loss_e.asnumpy(),
                               rtol=1e-6, atol=1e-6)


def test_module_fused_update_matches_eager(monkeypatch, tmp_path):
    """Module.update rides FusedApply (same machinery/numerics as the
    gluon fused step) — results must match the eager per-param loop."""
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.module import Module

    def run(env):
        if env is not None:
            monkeypatch.setenv("MXT_FUSED_STEP", env)
        else:
            monkeypatch.delenv("MXT_FUSED_STEP", raising=False)
        mx.random.seed(0)
        rng = np.random.RandomState(0)
        x = rng.uniform(-1, 1, (32, 8)).astype(np.float32)
        y = rng.randint(0, 4, (32,)).astype(np.float32)
        data = sym.var("data")
        net = sym.FullyConnected(data, num_hidden=16, name="fc1")
        net = sym.Activation(net, act_type="relu")
        net = sym.FullyConnected(net, num_hidden=4, name="fc2")
        net = sym.SoftmaxOutput(net, name="softmax")
        mod = Module(net, data_names=("data",),
                     label_names=("softmax_label",))
        it = NDArrayIter(x, y, batch_size=8)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params(initializer=mx.init.Uniform(0.05))
        mod.init_optimizer(optimizer="sgd", optimizer_params=(
            ("learning_rate", 0.1), ("momentum", 0.9)))
        for _ in range(2):
            it.reset()
            for batch in it:
                mod.forward(batch, is_train=True)
                mod.backward()
                mod.update()
        arg, aux = mod.get_params()
        return {k: v.asnumpy() for k, v in arg.items()}, mod

    wf, mod_f = run(None)
    assert mod_f._fused_update, "fused Module.update should be eligible"
    we, mod_e = run("0")
    assert mod_e._fused_update is False
    assert wf.keys() == we.keys()
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-6, atol=1e-6,
                                   err_msg=k)


# ---------------------------------------------------------------------------
# DataLoader prefetch (gluon/data/dataloader.py — _DevicePrefetcher):
# prefetched batches must equal non-prefetched ones in value AND order.
# ---------------------------------------------------------------------------
def test_dataloader_prefetch_matches():
    from mxnet_tpu.gluon import data as gdata

    rng = np.random.RandomState(0)
    npx = rng.uniform(0, 1, (37, 3)).astype(np.float32)
    npy = np.arange(37).astype(np.float32)
    ds = gdata.ArrayDataset(npx, npy)

    def collect(**kw):
        return [(bx.asnumpy(), by.asnumpy())
                for bx, by in gdata.DataLoader(ds, batch_size=5, **kw)]

    plain = collect()
    assert len(plain) == 8
    for kw in ({"prefetch": 2},                          # serial load-ahead
               {"prefetch": 3, "prefetch_to_device": True},
               {"num_workers": 2, "prefetch_to_device": True}):
        got = collect(**kw)
        assert len(got) == len(plain), kw
        for (ax, ay), (bx, by) in zip(plain, got):
            np.testing.assert_array_equal(ax, bx)
            np.testing.assert_array_equal(ay, by)


def test_dataloader_ndarray_samples_batched_read():
    """NDArray samples batchify through ONE stacked device op — values
    and dtypes must match the per-sample numpy stacking it replaced."""
    from mxnet_tpu.gluon import data as gdata

    rng = np.random.RandomState(0)
    npx = rng.uniform(0, 1, (10, 3)).astype(np.float32)
    ds = gdata.SimpleDataset(
        [(nd.array(npx[i]), nd.array([float(i)])) for i in range(10)])
    batches = list(gdata.DataLoader(ds, batch_size=4))
    assert len(batches) == 3
    bx, by = batches[0]
    assert bx.dtype == np.float32 and bx.shape == (4, 3)
    np.testing.assert_allclose(bx.asnumpy(), npx[:4], rtol=1e-7)
    np.testing.assert_array_equal(
        by.asnumpy().ravel(), np.arange(4, dtype=np.float32))
