"""Fused (one-launch, donated) Trainer.step vs the eager per-param path.

The canonical Gluon loop (ref: gluon/trainer.py — step) must produce
identical numerics whether Trainer.step runs the fused donated XLA program
or the eager per-parameter updates; these tests pin that equivalence and
the eligibility/fallback edges.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import nd
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon.trainer import _FusedUpdate


def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="fused_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    return net


def _train(net, trainer, steps=4, seed=0):
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        x = nd.array(rng.uniform(-1, 1, (8, 8)).astype(np.float32))
        y = nd.array(rng.uniform(-1, 1, (8, 4)).astype(np.float32))
        with ag.record():
            out = net(x)
            loss = ((out - y) ** 2).mean()
        loss.backward()
        trainer.step(8)
        losses.append(float(loss.asnumpy()))
    return losses


def _weights(net):
    return {k: v.data().asnumpy() for k, v in net.collect_params().items()}


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
    ("adamw", {"learning_rate": 1e-2, "wd": 1e-2}),
    ("rmsprop", {"learning_rate": 1e-3}),
    ("rmsprop", {"learning_rate": 1e-3, "centered": True}),
    ("adagrad", {"learning_rate": 0.05, "wd": 1e-4}),
])
def test_fused_matches_eager(monkeypatch, optimizer, opt_params):
    net_f = _make_net()
    tr_f = Trainer(net_f.collect_params(), optimizer, dict(opt_params))
    _train(net_f, tr_f)
    assert tr_f._fused, "fused path should be eligible here"

    monkeypatch.setenv("MXT_FUSED_TRAINER", "0")
    net_e = _make_net()
    tr_e = Trainer(net_e.collect_params(), optimizer, dict(opt_params))
    _train(net_e, tr_e)
    assert tr_e._fused is False

    wf, we = _weights(net_f), _weights(net_e)
    assert wf.keys() == we.keys()
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)
    # optimizer step counters advanced identically
    assert tr_f._optimizer.num_update == tr_e._optimizer.num_update == 4


def test_fused_with_lr_scheduler(monkeypatch):
    from mxnet_tpu.lr_scheduler import FactorScheduler

    def run(env):
        if env is not None:
            monkeypatch.setenv("MXT_FUSED_TRAINER", env)
        net = _make_net()
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.5, "momentum": 0.9,
                      "lr_scheduler": FactorScheduler(step=2, factor=0.5)})
        _train(net, tr, steps=5)
        return _weights(net), tr

    wf, tr_f = run(None)
    assert tr_f._fused
    we, _ = run("0")
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-5, atol=1e-6)


def test_fused_lr_mult(monkeypatch):
    def run(env):
        if env is not None:
            monkeypatch.setenv("MXT_FUSED_TRAINER", env)
        net = _make_net()
        for name, p in net.collect_params().items():
            if name.endswith("bias"):
                p.lr_mult = 0.0  # frozen biases exercise the static fold
        tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.2})
        _train(net, tr)
        return _weights(net)

    wf = run(None)
    we = run("0")
    for k in wf:
        np.testing.assert_allclose(wf[k], we[k], rtol=1e-5, atol=1e-6)
    # the frozen biases really didn't move
    net0 = _make_net()
    w0 = _weights(net0)
    for k in wf:
        if k.endswith("bias"):
            np.testing.assert_array_equal(wf[k], w0[k])


def test_fused_save_load_states_roundtrip(tmp_path):
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    _train(net, tr, steps=3)
    assert tr._fused
    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)

    net2 = _make_net()
    tr2 = Trainer(net2.collect_params(), "adam", {"learning_rate": 1e-2})
    _train(net2, tr2, steps=1)  # materialize states
    tr2.load_states(fname)
    # the fused program closed over the pre-load optimizer — must rebuild
    assert tr2._fused is None
    # update counts resumed from the checkpoint, not the stale object
    assert tr2._optimizer.num_update == tr._optimizer.num_update == 3
    for i, s in tr._updaters[0].states.items():
        s2 = tr2._updaters[0].states[i]
        np.testing.assert_allclose(s[0].asnumpy(), s2[0].asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(s[1].asnumpy(), s2[1].asnumpy(),
                                   rtol=1e-6)
    # training continues through the fused path after a state load
    _train(net2, tr2, steps=1)


def test_fused_ineligible_falls_back():
    net = _make_net()
    # adadelta has no fused builder — must run eager and still train
    tr = Trainer(net.collect_params(), "adadelta", {"learning_rate": 1.0})
    losses = _train(net, tr)
    assert tr._fused is False
    assert np.isfinite(losses[-1])


def test_fused_no_per_step_retrace(monkeypatch):
    """Dynamic scalars (t, lr, rescale) are traced arguments, so the jit
    cache must stop growing after step 1 (step 0 compiles once; step 1
    recompiles once when the donated outputs re-enter as inputs) — a
    growing cache would mean a compile per step."""
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    _train(net, tr, steps=2)
    fused = tr._fused
    assert isinstance(fused, _FusedUpdate)
    steady = fused._jit._cache_size()
    _train(net, tr, steps=3, seed=1)
    assert fused._jit._cache_size() == steady <= 2


def test_tied_parameters_survive_donation():
    """Weight tying (params=other.params, the BERT MLM-decoder pattern)
    must register the tied Parameter in the borrowing block's
    collect_params(), so CachedOp passes it as a live input rather than
    baking it in as a constant — which dies as soon as the fused trainer
    donates the buffer."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import Block

    mx.random.seed(0)

    class Tied(Block):
        def __init__(self):
            super().__init__(prefix="tied_")
            with self.name_scope():
                self.embed = nn.Embedding(20, 8)
                self.decoder = nn.Dense(20, flatten=False, in_units=8,
                                        params=self.embed.params)

        def forward(self, x):
            return self.decoder(self.embed(x))

    net = Tied()
    net.initialize()
    # the tied weight must appear in the BORROWING block's params too
    tied_name = net.embed.weight.name
    assert net.decoder.weight is net.embed.weight  # actually tied
    assert tied_name in net.decoder.collect_params()
    assert len(net.collect_params()) == 2  # tied weight + decoder bias
    net.embed.hybridize()
    net.decoder.hybridize()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    x = nd.array(np.arange(6).reshape(2, 3).astype("f4"))
    y = nd.array(np.ones((2, 3), "f4"))
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(3):  # step 2+ would hit the deleted donated buffer
        with ag.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        tr.step(2)
    assert np.isfinite(float(loss.asnumpy()))


def test_tied_parameter_shape_mismatch_raises():
    from mxnet_tpu.gluon import Block

    mx.random.seed(0)
    with pytest.raises(mx.MXNetError, match="tied parameter"):
        class Bad(Block):
            def __init__(self):
                super().__init__(prefix="badtied_")
                with self.name_scope():
                    self.embed = nn.Embedding(20, 8)
                    # in_units=9 conflicts with the tied (20, 8) weight
                    self.decoder = nn.Dense(20, in_units=9,
                                            params=self.embed.params)

        Bad()
