"""Value-level parity of core NN ops against torch (CPU oracle).

The numeric-gradient sweep checks our backward against our forward;
these tests check the FORWARD semantics themselves against an
independent implementation of the same reference ops (torch implements
the identical conv/pool/norm contracts the reference's mshadow/cuDNN
kernels do). Gradients for conv/FC are cross-checked too.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402


def _np32(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed)
            .uniform(-1, 1, shape).astype(np.float32) * scale)


@pytest.mark.parametrize("stride,pad,dilate,groups", [
    (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 2)])
def test_conv2d_forward_backward(stride, pad, dilate, groups):
    x_np = _np32(2, 4, 10, 10, seed=1)
    w_np = _np32(6, 4 // groups, 3, 3, seed=2)
    b_np = _np32(6, seed=3)

    x = mx.nd.array(x_np)
    w = mx.nd.array(w_np)
    b = mx.nd.array(b_np)
    for a in (x, w, b):
        a.attach_grad()
    with autograd.record():
        out = mx.nd.Convolution(x, w, b, kernel=(3, 3),
                                stride=(stride, stride),
                                pad=(pad, pad), dilate=(dilate, dilate),
                                num_filter=6, num_group=groups)
        loss = (out * out).sum()
    loss.backward()

    tx = torch.from_numpy(x_np).requires_grad_()
    tw = torch.from_numpy(w_np).requires_grad_()
    tb = torch.from_numpy(b_np).requires_grad_()
    tout = tF.conv2d(tx, tw, tb, stride=stride, padding=pad,
                     dilation=dilate, groups=groups)
    (tout * tout).sum().backward()

    np.testing.assert_allclose(out.asnumpy(), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(x.grad.asnumpy(), tx.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(w.grad.asnumpy(), tw.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(b.grad.asnumpy(), tb.grad.numpy(),
                               rtol=1e-3, atol=1e-3)


def test_deconv2d_forward():
    x_np = _np32(2, 3, 5, 5, seed=4)
    w_np = _np32(3, 4, 3, 3, seed=5)  # (in, out, kH, kW) — both contracts
    out = mx.nd.Deconvolution(mx.nd.array(x_np), mx.nd.array(w_np),
                              kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                              adj=(1, 1), num_filter=4, no_bias=True)
    tout = tF.conv_transpose2d(torch.from_numpy(x_np),
                               torch.from_numpy(w_np), stride=2,
                               padding=1, output_padding=1)
    np.testing.assert_allclose(out.asnumpy(), tout.numpy(), rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("pool_type,torch_fn", [
    ("max", tF.max_pool2d), ("avg", tF.avg_pool2d)])
def test_pooling(pool_type, torch_fn):
    x_np = _np32(2, 3, 8, 8, seed=6)
    out = mx.nd.Pooling(mx.nd.array(x_np), kernel=(2, 2), stride=(2, 2),
                        pool_type=pool_type)
    tout = torch_fn(torch.from_numpy(x_np), 2, 2)
    np.testing.assert_allclose(out.asnumpy(), tout.numpy(), rtol=1e-5,
                               atol=1e-6)


def test_global_pooling():
    x_np = _np32(2, 3, 7, 5, seed=7)
    out = mx.nd.Pooling(mx.nd.array(x_np), kernel=(1, 1),
                        pool_type="avg", global_pool=True)
    ref = x_np.mean(axis=(2, 3), keepdims=True)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


def test_batchnorm_training_stats():
    x_np = _np32(4, 3, 6, 6, seed=8)
    gamma = _np32(3, seed=9) + 1.5
    beta = _np32(3, seed=10)
    x = mx.nd.array(x_np)
    mean0 = mx.nd.zeros((3,))
    var0 = mx.nd.ones((3,))
    with autograd.record():  # training mode -> batch stats
        out = mx.nd.BatchNorm(x, mx.nd.array(gamma), mx.nd.array(beta),
                              mean0, var0, fix_gamma=False, eps=1e-5,
                              momentum=0.9)
    tout = tF.batch_norm(torch.from_numpy(x_np), None, None,
                         torch.from_numpy(gamma),
                         torch.from_numpy(beta), training=True,
                         eps=1e-5)
    y = out[0] if isinstance(out, tuple) else out
    np.testing.assert_allclose(y.asnumpy(), tout.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_layernorm_parity():
    x_np = _np32(4, 10, seed=11)
    g = _np32(10, seed=12) + 1.0
    b = _np32(10, seed=13)
    out = mx.nd.LayerNorm(mx.nd.array(x_np), mx.nd.array(g),
                          mx.nd.array(b), eps=1e-5)
    tout = tF.layer_norm(torch.from_numpy(x_np), (10,),
                         torch.from_numpy(g), torch.from_numpy(b),
                         eps=1e-5)
    np.testing.assert_allclose(out.asnumpy(), tout.numpy(), rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("act,tfn", [
    ("relu", tF.relu), ("sigmoid", torch.sigmoid), ("tanh", torch.tanh),
    ("softrelu", tF.softplus)])
def test_activations(act, tfn):
    x_np = _np32(3, 7, seed=14, scale=3.0)
    out = mx.nd.Activation(mx.nd.array(x_np), act_type=act)
    np.testing.assert_allclose(out.asnumpy(),
                               tfn(torch.from_numpy(x_np)).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_softmax_log_softmax_pick():
    x_np = _np32(4, 9, seed=15, scale=4.0)
    np.testing.assert_allclose(
        mx.nd.softmax(mx.nd.array(x_np)).asnumpy(),
        tF.softmax(torch.from_numpy(x_np), dim=-1).numpy(),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.log_softmax(mx.nd.array(x_np)).asnumpy(),
        tF.log_softmax(torch.from_numpy(x_np), dim=-1).numpy(),
        rtol=1e-5, atol=1e-6)


def test_fully_connected_grads():
    x_np = _np32(5, 7, seed=16)
    w_np = _np32(4, 7, seed=17)
    b_np = _np32(4, seed=18)
    x, w, b = (mx.nd.array(a) for a in (x_np, w_np, b_np))
    for a in (x, w, b):
        a.attach_grad()
    with autograd.record():
        out = mx.nd.FullyConnected(x, w, b, num_hidden=4)
        ((out * out).sum()).backward()
    tx = torch.from_numpy(x_np).requires_grad_()
    tw = torch.from_numpy(w_np).requires_grad_()
    tb = torch.from_numpy(b_np).requires_grad_()
    tout = tF.linear(tx, tw, tb)
    (tout * tout).sum().backward()
    np.testing.assert_allclose(out.asnumpy(), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    for ours, theirs in ((x, tx), (w, tw), (b, tb)):
        np.testing.assert_allclose(ours.grad.asnumpy(),
                                   theirs.grad.numpy(), rtol=1e-3,
                                   atol=1e-4)


def test_embedding_take_gather():
    table = _np32(11, 5, seed=19)
    idx = np.array([[1, 4, 7], [0, 10, 3]], dtype=np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(table),
                          input_dim=11, output_dim=5)
    ref = tF.embedding(torch.from_numpy(idx.astype(np.int64)),
                       torch.from_numpy(table))
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-6)


def test_rnn_fused_lstm_vs_torch():
    """The packed-parameter fused LSTM against torch.nn.LSTM with the
    same weights."""
    T, B, I, H = 6, 3, 4, 5
    from mxnet_tpu.ops.rnn import rnn_param_size

    rng = np.random.RandomState(20)
    x_np = rng.uniform(-1, 1, (T, B, I)).astype(np.float32)

    lstm = torch.nn.LSTM(I, H, num_layers=1)
    with torch.no_grad():
        for p in lstm.parameters():
            p.uniform_(-0.5, 0.5)
    # pack into ops/rnn.py layout: wi, wh (all layers), then bi, bh
    wi = lstm.weight_ih_l0.detach().numpy()   # (4H, I) gate order i,f,g,o
    wh = lstm.weight_hh_l0.detach().numpy()
    bi = lstm.bias_ih_l0.detach().numpy()
    bh = lstm.bias_hh_l0.detach().numpy()
    packed = np.concatenate([wi.ravel(), wh.ravel(), bi, bh])
    assert packed.shape[0] == rnn_param_size("lstm", I, H)

    out = mx.nd.RNN(mx.nd.array(x_np), mx.nd.array(packed),
                    mx.nd.zeros((1, B, H)), mx.nd.zeros((1, B, H)),
                    mode="lstm", state_size=H, num_layers=1,
                    state_outputs=True)
    tout, (th, tc) = lstm(torch.from_numpy(x_np))
    np.testing.assert_allclose(out[0].asnumpy(), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out[1].asnumpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out[2].asnumpy(), tc.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
