"""Native C++ RecordIO engine (mxnet_tpu/src/recordio.cc via native.py):
byte-format parity with the pure-Python reader, threaded prefetch order,
and the ImageRecordIter fast path. Skipped wholesale when no toolchain."""
import os

import numpy as np
import pytest

from mxnet_tpu import native, recordio

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native engine unavailable")


@pytest.fixture
def shard(tmp_path):
    p = str(tmp_path / "t.rec")
    rng = np.random.RandomState(0)
    payloads = [bytes(rng.randint(0, 256, rng.randint(1, 3000),
                                  dtype=np.uint8)) for _ in range(150)]
    w = recordio.MXRecordIO(p, "w")
    for pl in payloads:
        w.write(pl)
    w.close()
    return p, payloads


def test_scan_matches_python_walk(shard):
    p, payloads = shard
    r = native.NativeRecordReader(p)
    offs, lens = r.scan()
    assert len(offs) == len(payloads)
    assert list(lens) == [len(pl) for pl in payloads]
    # python reader sees records at offs - 8
    pr = recordio.MXRecordIO(p, "r")
    for i in (0, 1, 73, 149):
        pr.handle.seek(int(offs[i]) - 8)
        assert pr.read() == payloads[i]
    pr.close()


def test_random_and_sequential_reads(shard):
    p, payloads = shard
    r = native.NativeRecordReader(p)
    for i in (149, 0, 42):
        assert r.read(i) == payloads[i]
    r2 = native.NativeRecordReader(p)
    got = []
    while True:
        b = r2.read_next()
        if b is None:
            break
        got.append(b)
    assert got == payloads


def test_corrupt_magic_detected(tmp_path):
    p = str(tmp_path / "bad.rec")
    with open(p, "wb") as f:
        f.write(b"\x00" * 64)
    r = native.NativeRecordReader(p)
    with pytest.raises(RuntimeError, match="corrupt"):
        r.scan()


def test_prefetch_shuffled_order(shard):
    p, payloads = shard
    r = native.NativeRecordReader(p)
    offs, lens = r.scan()
    order = np.random.RandomState(1).permutation(len(payloads))
    pf = native.NativePrefetcher(p, offs, lens, order,
                                 num_threads=3, capacity=8)
    out = list(pf)
    assert [out[j] for j in range(len(order))] \
        == [payloads[i] for i in order]


def test_prefetch_early_stop(shard):
    p, payloads = shard
    r = native.NativeRecordReader(p)
    offs, lens = r.scan()
    pf = native.NativePrefetcher(p, offs, lens, np.arange(len(payloads)),
                                 num_threads=2, capacity=4)
    assert pf.pop() == payloads[0]
    pf.stop()  # must join workers without deadlock
    assert pf.pop() is None


def test_image_record_iter_uses_native(tmp_path):
    from mxnet_tpu.io import ImageRecordIter

    p = str(tmp_path / "img.rec")
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(p, "w")
    for i in range(20):
        img = rng.randint(0, 255, (40, 40, 3), dtype=np.uint8)
        w.write(recordio.pack_img((0, float(i % 4), i, 0), img,
                                  img_fmt=".png"))
    w.close()

    it = ImageRecordIter(path_imgrec=p, data_shape=(3, 32, 32),
                         batch_size=5, shuffle=False,
                         preprocess_threads=3)
    assert it._native is not None  # fast path engaged
    batches = list(it)
    assert len(batches) == 4
    for b in batches:
        assert b.data[0].shape == (5, 3, 32, 32)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    np.testing.assert_array_equal(labels, np.arange(20) % 4)


def test_record_file_dataset_native_path(tmp_path):
    from mxnet_tpu.gluon.data import RecordFileDataset

    p = str(tmp_path / "ds.rec")
    rng = np.random.RandomState(3)
    payloads = [bytes(rng.randint(0, 256, 100 + i, dtype=np.uint8))
                for i in range(40)]
    w = recordio.MXIndexedRecordIO(str(tmp_path / "ds.idx"), p, "w")
    for i, pl in enumerate(payloads):
        w.write_idx(i, pl)
    w.close()

    ds = RecordFileDataset(p)
    assert ds._payload is not None  # native fast path engaged
    assert len(ds) == 40
    for i in (0, 17, 39):
        assert ds[i] == payloads[i]

    # threaded readers (DataLoader worker pattern) agree
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        got = list(pool.map(lambda i: ds[i], range(40)))
    assert got == payloads


def test_record_file_dataset_stale_idx_falls_back(tmp_path):
    from mxnet_tpu.gluon.data import RecordFileDataset

    p = str(tmp_path / "ds2.rec")
    idx = str(tmp_path / "ds2.idx")
    w = recordio.MXIndexedRecordIO(idx, p, "w")
    for i in range(5):
        w.write_idx(i, b"x" * (10 + i))
    w.close()
    # corrupt the sidecar offsets (regenerated .rec scenario)
    with open(idx, "w") as f:
        for i in range(5):
            f.write("%d\t%d\n" % (i, 1000 + i))
    ds = RecordFileDataset(p)
    assert ds._payload is None  # fell back to the python reader


def test_image_record_iter_prefetch_across_epochs(tmp_path):
    """Shuffled epochs through the native read-ahead ring stay correct:
    every epoch yields exactly the full label set, in the shuffled
    order's sequence, across resets."""
    from mxnet_tpu.io import ImageRecordIter

    p = str(tmp_path / "pf.rec")
    rng = np.random.RandomState(1)
    w = recordio.MXRecordIO(p, "w")
    for i in range(30):
        img = rng.randint(0, 255, (36, 36, 3), dtype=np.uint8)
        w.write(recordio.pack_img((0, float(i), i, 0), img,
                                  img_fmt=".png"))
    w.close()

    it = ImageRecordIter(path_imgrec=p, data_shape=(3, 32, 32),
                         batch_size=8, shuffle=True, seed=5,
                         preprocess_threads=2)
    assert it._prefetcher is not None
    for epoch in range(3):
        labels = []
        for batch in it:
            labels.extend(batch.label[0].asnumpy()
                          [:8 - batch.pad if batch.pad else 8])
        # round_batch wraps: first len-pad labels of the last batch are
        # the tail; the full multiset must be 0..29
        assert sorted(int(v) for v in labels) == list(range(30))
        it.reset()
